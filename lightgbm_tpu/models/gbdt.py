"""Gradient-boosting orchestration.

Reference: src/boosting/gbdt.cpp (GBDT::{Init,TrainOneIter,UpdateScore,
RollbackOneIter}), gbdt_model_text.cpp (SaveModelToString/LoadModelFromString),
dart.hpp, rf.hpp, sample_strategy.cpp / bagging.hpp / goss.hpp,
score_updater.hpp.

TPU-first structure: the boosting loop stays in Python (it is inherently
sequential — one tree depends on the previous scores), but every O(N) step is
a jitted device op: gradient computation, tree growth (ops/treegrow.py), and
the score update, which is a pure gather `score += leaf_value[leaf_id]` since
tree growth maintains per-row leaf ids for ALL rows (the partition-based fast
path of ScoreUpdater::AddScore).
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..metrics import Metric, create_metrics
from ..objectives import Objective, create_objective
from ..obs import metrics as _obs
from ..obs import trace as _trace
from ..ops.split import SplitParams
from ..ops.treegrow import grow_tree
from ..ops import predict as predict_ops
from ..utils import faults as _faults
from ..utils import profiling as _profiling  # noqa: F401 — importing
# installs the jax.profiler span-annotation bridge when
# LGBMTPU_JAX_PROFILER=1 (obs/ itself must stay jax-free)
from ..utils import locktrace as _lt
from ..utils import sanitizer as _san
from .tree import Tree, tree_from_device

_MODEL_VERSION = "v4"

# serving bucket ladder: predict batches pad N up to the next power of two
# (floor 8) so the jitted traversal compiles once per bucket instead of once
# per distinct batch size — the predict-side analogue of the windowed
# grower's W ladder.  Padding rows are masked on device; the padded result
# is bit-identical to the unpadded one (rows traverse independently).
_PREDICT_BUCKET_MIN = 8


def _predict_bucket(n: int) -> int:
    """Row-bucket for a batch of n rows; LGBMTPU_PREDICT_BUCKETS=0 disables
    (exact shapes — one compile per distinct N, the pre-round-9 behavior)."""
    if os.environ.get("LGBMTPU_PREDICT_BUCKETS", "1") == "0":
        return n
    b = _PREDICT_BUCKET_MIN
    while b < n:
        b <<= 1
    return b


@functools.lru_cache(maxsize=64)
def _sharded_raw_entry(mesh, k: int, has_cat: bool):
    """Giant-batch serving entry: the stacked traversal as ONE SPMD
    dispatch over the row ("data") axis of ``mesh``.

    Rows traverse independently and the per-row tree sum keeps the exact
    single-device reduction order inside each rank, so the row-sharded
    result is BITWISE the single-device ``predict_raw`` — the same
    property that makes the bucket ladder safe makes the row split safe.
    The body has ZERO collectives (each rank emits exactly its own row
    block); the packed per-tree tables ride replicated.  On a 2-D
    (feature x row) training mesh ``P(data)`` shards rows and replicates
    over the feature axis, so the training mesh is directly servable."""
    from jax.sharding import PartitionSpec as _P

    from ..parallel.compat import shard_map as _smap
    from ..parallel.mesh import DATA_AXIS as _AX

    row, rep = _P(_AX), _P()

    def run(x, active, sf, th, dl, mt, lc, rc, nl, lv, *cat):
        ckw = {}
        if has_cat:
            ckw = dict(is_cat=cat[0], cat_base=cat[1], cat_nwords=cat[2],
                       cat_words=cat[3])
        if k == 1:
            return predict_ops.predict_raw_values(
                x, sf, th, dl, mt, lc, rc, nl, lv, active=active, **ckw)
        return predict_ops.predict_raw_multiclass(
            x, sf, th, dl, mt, lc, rc, nl, lv, active=active, k=k, **ckw)

    in_specs = (row, row) + (rep,) * (8 + (4 if has_cat else 0))
    return jax.jit(_smap(run, mesh=mesh, in_specs=in_specs, out_specs=row,
                         check_vma=False))


def _dummy_tree() -> Tree:
    """Single-leaf zero-value tree: pads the tree axis of a packed ensemble
    so every early-stop window has the same static size (contributes exactly
    0.0 to every row — leaf 0 of a num_leaves=1 tree)."""
    z32 = np.zeros(0, np.int32)
    return Tree(
        num_leaves=1, split_feature=z32, threshold=np.zeros(0, np.float64),
        threshold_bin=None, decision_type=np.zeros(0, np.uint8),
        split_gain=np.zeros(0, np.float32), left_child=z32, right_child=z32,
        internal_value=np.zeros(0, np.float64),
        internal_weight=np.zeros(0, np.float64),
        internal_count=np.zeros(0, np.int64),
        leaf_value=np.zeros(1, np.float64),
        leaf_weight=np.zeros(1, np.float64),
        leaf_count=np.zeros(1, np.int64),
    )


@functools.partial(jax.jit, donate_argnums=(0,))
def _add_leaf_scores(score, leaf_value, leaf_id, shrinkage):
    return score + leaf_value[leaf_id] * shrinkage


def _f32_threshold_upper(t: np.ndarray) -> np.ndarray:
    """Round f64 thresholds UP to f32 so the device's f32 traversal keeps the
    invariant `v <= t (f64)  =>  f32(v) <= t32`: rows left of the split stay
    left.  (Plain nearest-rounding can err in both directions; the remaining
    right-side window (t, t32] is below one f32 ulp — reference traverses in
    double, include/LightGBM/tree.h NumericalDecision.)"""
    t = np.asarray(t, np.float64)
    t32 = t.astype(np.float32)
    bump = t32.astype(np.float64) < t
    return np.where(bump, np.nextafter(t32, np.float32(np.inf)), t32)


def _quantized_wide_default(*, on_tpu: bool, n_features: int,
                            max_num_bins: int, tree_learner: str,
                            tree_growth_mode: str, explicitly_set: bool,
                            has_monotone: bool, device_count: int = 1) -> bool:
    """TPU device default for int8 quantized training: only the WIDE
    wide-bin regime on the rounds grower, never overriding an explicit
    user choice, never with monotone constraints (renewal interplay).
    Pure predicate so the gate is unit-testable off-chip (the suite runs
    CPU-pinned).  tree_learner='data' takes the rounds grower only with
    multiple devices (_use_fast_dp's gate); single-device 'data' runs the
    strict grower, which trains float — enabling the default there would
    just produce contradictory logs."""
    rounds_grower = (
        (tree_learner == "serial"
         or (tree_learner == "data" and device_count > 1))
        and (tree_growth_mode == "rounds"
             or (tree_growth_mode == "auto" and on_tpu))
    )
    return (on_tpu and max_num_bins > 64 and n_features >= 256
            and rounds_grower and not explicitly_set and not has_monotone)


# guards lazy _pack_lock creation on instances that predate the lock
# (unpickled state, legacy deepcopies) — see GBDT._plock
_PACK_LOCK_INIT = _lt.lock("gbdt.pack_init")


class GBDT:
    """reference: class GBDT in src/boosting/gbdt.h."""

    average_output = False  # RF mode: predictions are averaged over trees

    def __init__(self, cfg: Config, train_set=None, objective: Optional[Objective] = None):
        self.cfg = cfg
        self.objective = objective if objective is not None else create_objective(cfg)
        self.train_set = None
        self._models: List[Tree] = []  # flattened: iter-major, class-minor
        # device trees not yet materialized to host (fast async path): the
        # round-batched grower runs whole iterations without host syncs and
        # trees are converted lazily on first host access (save/predict/...)
        self._pending: List[tuple] = []
        self.iter_ = 0
        self.num_tree_per_iteration = cfg.num_tree_per_iteration
        self.init_scores = [0.0] * self.num_tree_per_iteration
        self.best_iteration = -1
        self.feature_names: List[str] = []
        self.metrics: List[Metric] = []
        self.train_name = "training"  # overridable via valid_names (engine.py)
        self.valid_sets: List = []
        self.valid_names: List[str] = []
        self._valid_scores: List[jnp.ndarray] = []
        self._pred_cache = None
        self._pack_version = 0  # bumped by _invalidate_pred_cache
        # pack lock (round 19, lightgbm_tpu/continual): trainer-thread
        # mutations (refit/append under a live ServingRuntime) bump
        # _pack_version and evict stale entries UNDER THE SAME LOCK the
        # serving threads' _packed lookup/insert holds — an unlocked
        # bump racing a lookup could evict a dict entry mid-iteration or
        # publish a pack under a version it no longer belongs to
        self._pack_lock = _lt.rlock("gbdt.pack")
        self.binner = None
        self.rng = np.random.RandomState(cfg.seed)
        # non-finite guard rail (docs/ROBUSTNESS.md): first boosting
        # iteration (1-based) whose tree carried NaN/inf, 0 = clean.
        # Accumulated ON DEVICE per iteration (O(num_leaves), no syncs)
        # and pulled only at points that already sync (_guard_check)
        self._guard_bad_iter = jnp.asarray(0, jnp.int32)
        # telemetry is default-on and process-wide (docs/OBSERVABILITY.md);
        # an explicit telemetry= param applies for this model's lifetime,
        # and a model WITHOUT one restores the process default — so one
        # model's telemetry=false cannot silently swallow a later model's
        # metrics_file= snapshot
        _obs.set_enabled(bool(cfg.telemetry) if cfg.is_set("telemetry")
                         else _obs.DEFAULT_ENABLED)
        if train_set is not None:
            self.reset_training_data(train_set)

    # ------------------------------------------------------------------
    @property
    def models(self) -> List[Tree]:
        """Host trees; converts any pending device trees first (the fast
        grower defers tree_from_device so training never blocks on the
        host<->device round-trip — reference keeps trees host-side always)."""
        self._flush_pending()
        return self._models

    @models.setter
    def models(self, value) -> None:
        self._pending = []
        self._models = value
        self._invalidate_pred_cache("models_setter")

    def _plock(self) -> "_lt.TracedLock":
        """The pack lock, lazily recreated for instances that predate it
        (unpickled/legacy state); creation races are excluded by the
        module-level init lock."""
        lock = getattr(self, "_pack_lock", None)
        if lock is None:
            with _PACK_LOCK_INIT:
                lock = getattr(self, "_pack_lock", None)
                if lock is None:
                    lock = self._pack_lock = _lt.rlock("gbdt.pack")
        return lock

    def __getstate__(self):
        # locks cannot be pickled/deepcopied; _plock recreates on demand
        d = dict(self.__dict__)
        d.pop("_pack_lock", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        # re-create the pack lock under the SAME init lock _plock uses:
        # the old unconditional assignment raced a concurrent _plock()
        # caller — it could mint lock A (and start serving under it)
        # between the __dict__ update and this line, after which the
        # overwrite published lock B and two threads held "the" pack
        # lock simultaneously.  Create-if-absent under _PACK_LOCK_INIT
        # makes exactly one lock win both paths.
        with _PACK_LOCK_INIT:
            if getattr(self, "_pack_lock", None) is None:
                self._pack_lock = _lt.rlock("gbdt.pack")

    def _invalidate_pred_cache(self, reason: str) -> None:
        """VERSION the packed-ensemble serving cache instead of nulling it
        (round 18, lightgbm_tpu/serve): a model mutation bumps
        ``_pack_version`` — the leading component of every ``_packed``
        key — so the next predict packs fresh under the new version while
        entries of the PREVIOUS version stay resident and servable.  A
        hot swap (refit / set_leaf_output / continued training under a
        live serving runtime) therefore never cools the cache for
        in-flight predicts: a reader that grabbed the pre-mutation pack
        keeps its device arrays, and a reader racing the bump still finds
        the old entry instead of rebuilding mid-request.  Versions older
        than ``_PACKED_KEEP_VERSIONS`` are evicted here, counted in
        ``predict_stale_pack_evictions_total``.  Real invalidations (a
        populated cache bumped) are counted so serving dashboards can see
        churn — training every round vs an occasional leaf edit look very
        different here.

        Round 19 (continual training) made the bump+evict ATOMIC with the
        serving threads' ``_packed`` lookup by sharing ``_pack_lock``: a
        trainer-thread refit/append racing a coalesced predict could
        otherwise evict dict entries mid-lookup or let a pack built
        against the pre-mutation trees publish under the post-mutation
        version (tests/test_continual.py hammers exactly this)."""
        with self._plock():
            if getattr(self, "_pred_cache", None):
                _obs.counter("predict_cache_invalidations_total").inc()
                _obs.event("pred_cache_invalidate", reason=reason,
                           version=self._pack_version + 1)
            self._pack_version = getattr(self, "_pack_version", 0) + 1
            cache = getattr(self, "_pred_cache", None)
            if cache:
                floor = self._pack_version - self._PACKED_KEEP_VERSIONS
                stale = [key for key in cache if key[0] <= floor]
                for key in stale:
                    del cache[key]
                if stale:
                    _obs.counter(
                        "predict_stale_pack_evictions_total").inc(len(stale))

    def _flush_pending(self) -> None:
        if self._pending:
            self._guard_check()
            pending, self._pending = self._pending, []
            for arrays, shrink, linear_fit in pending:
                tree = tree_from_device(arrays, self.binner, linear=linear_fit)
                tree.apply_shrinkage(shrink)
                self._models.append(tree)

    # -- non-finite guard rail (docs/ROBUSTNESS.md) --------------------
    def _guard_accumulate(self, arrays) -> None:
        """Fold this iteration's tree stats into the device-side guard
        flag: O(num_leaves) reductions, no host pull.  Mirrors the
        windowed grower's in-round info-vector guard on the full-pass and
        fast growers, which have no per-round host read to ride."""
        ok = (jnp.isfinite(arrays.leaf_value).all()
              & ~jnp.isnan(arrays.split_gain).any())
        self._guard_bad_iter = jnp.where(
            (self._guard_bad_iter == 0) & ~ok,
            jnp.asarray(self.iter_ + 1, jnp.int32), self._guard_bad_iter)

    def _guard_check(self) -> None:
        """Pull and test the guard flag — callers are points that sync
        anyway (eval, flush, save, the %32 finish probe), so detection
        lags corruption by at most the sync cadence while the error stays
        stamped with the iteration the corruption ENTERED."""
        bad = int(np.asarray(self._guard_bad_iter))
        if bad:
            from ..utils.guards import NonFiniteError

            _obs.counter("train_nonfinite_errors_total").inc()
            _obs.event("nonfinite", phase="guard_check", iteration=bad)
            raise NonFiniteError(
                f"non-finite leaf values/split gains entered the model at "
                f"boosting iteration {bad}: the gradients or hessians went "
                "NaN/inf (custom objective output? fp overflow?) and every "
                "tree from that iteration on is invalid. Detection is "
                "deferred to sync points by design — the device-side guard "
                "costs no extra dispatches; see docs/ROBUSTNESS.md")

    # ------------------------------------------------------------------
    def reset_training_data(self, train_set) -> None:
        """reference: GBDT::ResetTrainingData."""
        self._fused_step = None
        self._nobag_cache = None
        self._forced_cache = None
        self._eval_jit_cache = None
        self._finish_probe = None
        if self.cfg.num_machines > 1:
            # multi-host bring-up (reference: Network::Init from machine
            # list).  MUST run before the first JAX computation — so before
            # Dataset.construct uploads anything (jax.distributed.initialize
            # rejects an already-initialized backend).
            from ..parallel.distributed import init_distributed

            init_distributed(self.cfg)
        self.train_set = train_set
        train_set.construct()
        self.binner = train_set.binner
        self.feature_names = list(train_set.feature_names)
        self.metrics = create_metrics(self.cfg)
        n = train_set.num_data()
        k = self.num_tree_per_iteration
        self._label = jnp.asarray(train_set.label, dtype=jnp.float32)
        self._weight = (
            None if train_set.weight is None else jnp.asarray(train_set.weight, jnp.float32)
        )
        shape = (n,) if k == 1 else (n, k)
        init = np.zeros(shape, dtype=np.float32)
        if self.objective is not None and hasattr(self.objective, "prepare"):
            # label-dependent objective state (is_unbalance weights etc.) is
            # needed regardless of boost_from_average
            self.objective.prepare(np.asarray(train_set.label), train_set.weight)
        if self.objective is not None and self.cfg.boost_from_average and not self.models:
            # pre-partition multi-controller runs compute the init score from
            # the GLOBAL label distribution (reference: BoostFromScore syncs
            # via Network::GlobalSyncUpBySum); equal shard sizes required
            init_label, init_weight = self._label, self._weight
            if (
                self.cfg.pre_partition
                and jax.process_count() > 1
                and self.cfg.tree_learner in ("data", "voting")
            ):
                from jax.experimental import multihost_utils

                init_label = jnp.asarray(
                    multihost_utils.process_allgather(self._label, tiled=True)
                )
                if self._weight is not None:
                    init_weight = jnp.asarray(
                        multihost_utils.process_allgather(self._weight, tiled=True)
                    )
            if k == 1:
                self.init_scores = [self.objective.boost_from_score(init_label, init_weight)]
                init += np.float32(self.init_scores[0])
            else:
                # per-class init (reference: multiclass BoostFromScore per tree id)
                self.init_scores = []
                lbl_all = np.asarray(init_label)
                w_all = None if init_weight is None else np.asarray(init_weight)
                for c in range(k):
                    lbl = (lbl_all == c).astype(np.float32)
                    p = float(lbl.mean() if w_all is None else np.average(lbl, weights=w_all))
                    p = min(max(p, 1e-15), 1 - 1e-15)
                    self.init_scores.append(float(np.log(p / (1 - p))))
                init += np.asarray(self.init_scores, dtype=np.float32)[None, :]
        if train_set.init_score is not None:
            init += np.asarray(train_set.init_score, dtype=np.float32).reshape(shape)
        self._score = jnp.asarray(init)
        if self.objective is not None and hasattr(self.objective, "set_query") and train_set.query_boundaries is not None:
            self.objective.set_query(train_set.query_boundaries, np.asarray(train_set.label))
            if (
                hasattr(self.objective, "set_positions")
                and getattr(train_set, "position", None) is not None
            ):
                self.objective.set_positions(train_set.position)
        self._split_params = SplitParams(
            lambda_l1=self.cfg.lambda_l1,
            lambda_l2=self.cfg.lambda_l2,
            min_data_in_leaf=self.cfg.min_data_in_leaf,
            min_sum_hessian_in_leaf=self.cfg.min_sum_hessian_in_leaf,
            min_gain_to_split=self.cfg.min_gain_to_split,
            max_delta_step=self.cfg.max_delta_step,
            path_smooth=self.cfg.path_smooth,
            cat_l2=self.cfg.cat_l2,
            cat_smooth=self.cfg.cat_smooth,
            max_cat_threshold=self.cfg.max_cat_threshold,
            max_cat_to_onehot=self.cfg.max_cat_to_onehot,
            feature_fraction_bynode=self.cfg.feature_fraction_bynode,
            extra_trees=bool(self.cfg.extra_trees),
            monotone_penalty=self.cfg.monotone_penalty,
            cegb_tradeoff=self.cfg.cegb_tradeoff,
            cegb_penalty_split=self.cfg.cegb_penalty_split,
        )
        cat_mask = np.asarray(self.binner.categorical_mask)
        self._allowed_features = jnp.ones(cat_mask.shape, dtype=bool)
        # feature_pre_filter (reference: DatasetLoader — ignore features that
        # can never produce a split satisfying min_data_in_leaf, whatever the
        # threshold or missing direction).  Exact per-feature check on bin
        # counts; numerical features only (categorical splits are subsets).
        if (
            self.cfg.feature_pre_filter
            and self.cfg.min_data_in_leaf > 1
            and getattr(train_set, "bins", None) is not None
            # out_of_core never materializes the host matrix the filter
            # scans; the (purely optimizing) filter is skipped there
            and jax.process_count() <= 1
            # multi-controller: ranks may hold different row shards, so
            # local counts could derive DIVERGENT feature masks and break
            # the identical-SPMD-program invariant; the reference filters
            # from globally-synced sample counts — until counts are psum'd
            # here, skip the (purely optimizing) filter in that mode
        ):
            bins_h = np.asarray(train_set.bins)
            nbpf_h = np.asarray(train_set.binner.num_bins_per_feature)
            mbpf_h = np.asarray(train_set.binner.missing_bin_per_feature)
            md = int(self.cfg.min_data_in_leaf)
            n_rows_h, n_feat_h = bins_h.shape
            bmax = int(nbpf_h.max()) if n_feat_h else 1
            allowed = np.ones(n_feat_h, dtype=bool)
            # one flattened bincount per feature block (not F python loops);
            # block size bounds the (N, blk) int64 temp to ~128MB
            blk = max(1, 2**24 // max(n_rows_h, 1))
            for j0 in range(0, n_feat_h, blk):
                j1 = min(j0 + blk, n_feat_h)
                nb = j1 - j0
                flat = bins_h[:, j0:j1].astype(np.int64)
                flat += np.arange(nb, dtype=np.int64)[None, :] * bmax
                counts = np.bincount(flat.ravel(), minlength=nb * bmax).reshape(nb, bmax)
                for dj in range(nb):
                    j = j0 + dj
                    if cat_mask[j] or nbpf_h[j] <= 1:
                        continue
                    cm = counts[dj].copy()
                    m = int(cm[mbpf_h[j]]) if mbpf_h[j] >= 0 else 0
                    if mbpf_h[j] >= 0:
                        cm[mbpf_h[j]] = 0
                    p = np.cumsum(cm[: int(nbpf_h[j])])[:-1]  # left counts
                    if p.size == 0:
                        continue
                    q = (n_rows_h - m) - p
                    lo, hi = np.minimum(p, q), np.maximum(p, q)
                    # the missing mass may join the smaller side
                    if not np.any((hi >= md) & (lo + m >= md)):
                        allowed[j] = False
            if not allowed.all():
                from ..utils.log import log_info
                log_info(
                    f"feature_pre_filter: {int((~allowed).sum())} feature(s) "
                    f"cannot satisfy min_data_in_leaf={md} and were excluded"
                )
                self._allowed_features = jnp.asarray(allowed)
        # pass None when no categorical features so the all-numerical jit
        # graph skips the categorical candidate evaluation entirely
        self._categorical_mask = jnp.asarray(cat_mask) if cat_mask.any() else None
        # monotone constraints (reference: monotone_constraints.hpp, "basic")
        f = train_set.num_feature()
        mc = list(self.cfg.monotone_constraints or [])
        if mc and any(int(c) != 0 for c in mc):
            mc = (mc + [0] * f)[:f]
            self._monotone = jnp.asarray(np.asarray(mc, np.int32))
        else:
            self._monotone = None
        # per-feature split-gain multipliers (reference: config feature_contri
        # — gain[i] = max(0, contri[i]) * gain[i] in FindBestThreshold)
        fc = list(self.cfg.feature_contri or [])
        if fc and any(float(c) != 1.0 for c in fc):
            fc = (fc + [1.0] * f)[:f]
            self._feature_contri = jnp.asarray(np.asarray(fc, np.float32))
        else:
            self._feature_contri = None
        # interaction constraints (reference: config interaction_constraints
        # parsed into index sets; col_sampler.hpp filters per-leaf)
        sets = _parse_interaction_constraints(
            self.cfg.interaction_constraints, self.feature_names
        )
        if sets:
            mat = np.zeros((len(sets), f), dtype=bool)
            for i, st in enumerate(sets):
                for j in st:
                    if 0 <= j < f:
                        mat[i, j] = True
            self._interaction_sets = jnp.asarray(mat)
        else:
            self._interaction_sets = None
        self._needs_node_rng = bool(
            self.cfg.extra_trees or self.cfg.feature_fraction_bynode < 1.0
        )
        # growth scheduling: round-batched grower on TPU (tree_growth_mode)
        self._on_tpu = jax.devices()[0].platform == "tpu"
        if _quantized_wide_default(
                on_tpu=self._on_tpu,
                n_features=train_set.num_feature(),
                max_num_bins=train_set.max_num_bins,
                tree_learner=self.cfg.tree_learner,
                tree_growth_mode=self.cfg.tree_growth_mode,
                explicitly_set=self.cfg.is_set("use_quantized_grad"),
                has_monotone=self._monotone is not None,
                device_count=jax.device_count()):
            # TPU device default for the WIDE wide-bin regime: int8
            # quantized training.  The int8 payload carries 3 channels/leaf
            # (no bf16x2 split), doubling the Mosaic kernel's leaf tile and
            # halving admission rounds — measured Epsilon-class 400k x 2000
            # x 255 bins: 8.0 -> 5.1 s/iter.  At NARROW shapes the pass is
            # a single feature chunk and quantized ~= float within run
            # variance (measured 1M x 28 x 255: 10.7-10.9 vs 11.8 it/s),
            # so the default stays float there.  Stochastic rounding +
            # exact int32 accumulation + f32 leaf renewal keep AUC at parity
            # (0.93101 vs 0.93116 measured; docs/PERF_NOTES.md round 4).
            # An explicit use_quantized_grad either way always wins;
            # monotone runs stay float (renewal interplay, see warning
            # below).
            from ..utils.log import log_info
            self.cfg.use_quantized_grad = True
            if not self.cfg.is_set("quant_train_renew_leaf"):
                self.cfg.quant_train_renew_leaf = True
            log_info(
                "wide data with max_bin > 64 on TPU: enabling int8 "
                "quantized training (use_quantized_grad=true, leaf renewal "
                "on); set use_quantized_grad=false for the float path.")
        mode = self.cfg.tree_growth_mode
        self._use_fast = (
            self.cfg.tree_learner == "serial"
            and (mode == "rounds" or (mode == "auto" and self._on_tpu))
        )
        # rounds grower under SPMD data parallelism (voting/feature modes
        # stay on the strict grower — their cost is comms-shaped)
        self._use_fast_dp = (
            self.cfg.tree_learner == "data"
            and (mode == "rounds" or (mode == "auto" and self._on_tpu))
            and jax.device_count() > 1  # matches the _dp construction gate
        )
        # CEGB coupled per-feature penalties (reference: cegb.hpp); the
        # across-trees "feature already used anywhere" state lives here and
        # is updated on device after every tree
        if any(p != 0 for p in (self.cfg.cegb_penalty_feature_coupled or [])):
            pen = np.zeros(f, np.float32)
            for i, v in enumerate((self.cfg.cegb_penalty_feature_coupled or [])[:f]):
                pen[i] = self.cfg.cegb_tradeoff * float(v)
            self._cegb_coupled = jnp.asarray(pen)
            self._cegb_used_global = jnp.zeros((f,), bool)
        else:
            self._cegb_coupled = None
            self._cegb_used_global = None
        from ..utils.log import log_warning
        self.cfg.warn_na_params()
        if self.cfg.bagging_by_query and getattr(train_set, "query_boundaries", None) is None:
            log_warning("bagging_by_query is set but the dataset has no "
                        "query groups; falling back to row-wise bagging")
        if (
            self.cfg.forcedsplits_filename
            and self.cfg.tree_learner != "serial"
            and jax.device_count() > 1
        ):
            # the distributed wrappers (parallel/{data,feature}_parallel.py)
            # do not thread the forced schedule; warn instead of silently
            # dropping it (single-device runs fall back to the serial
            # growers, which DO apply it in both growth modes)
            log_warning(
                "forcedsplits_filename is not applied by the distributed "
                "tree learners (tree_learner=data/feature/voting on a "
                "multi-device mesh); use tree_learner=serial to force splits."
            )
        # CEGB lazy per-(row, feature) fetch charges (reference:
        # cost_effective_gradient_boosting.hpp feature_used_in_data): state
        # is (N, F) across trees, threaded through the strict serial grower
        if any(p != 0 for p in (self.cfg.cegb_penalty_feature_lazy or [])):
            lazy = np.zeros(f, np.float32)
            for i, v in enumerate((self.cfg.cegb_penalty_feature_lazy or [])[:f]):
                lazy[i] = self.cfg.cegb_tradeoff * float(v)
            self._cegb_lazy = jnp.asarray(lazy)
            self._cegb_lazy_used = jnp.zeros((train_set.num_data(), f), bool)
            if self.cfg.tree_learner != "serial" and jax.device_count() > 1:
                # the (N, F) charge state is row-global; the distributed
                # wrappers do not thread it across shards
                log_warning(
                    "cegb_penalty_feature_lazy is applied by the single-"
                    "device growers only (strict or rounds); this "
                    "distributed configuration IGNORES it."
                )
        else:
            self._cegb_lazy = None
            self._cegb_lazy_used = None
        if self._monotone is not None:
            mmethod = self.cfg.monotone_constraints_method
            if mmethod == "advanced":
                log_warning(
                    "monotone_constraints_method='advanced' is not "
                    "implemented; using 'intermediate' (measured headroom "
                    "bound: benchmarks/monotone_advanced_headroom.py)."
                )
            if (mmethod in ("intermediate", "advanced")
                    and self.cfg.use_quantized_grad
                    and self.cfg.quant_train_renew_leaf):
                log_warning(
                    "quant_train_renew_leaf is skipped under intermediate "
                    "monotone bounds: renewed leaf values cannot be "
                    "re-clipped to evolving bounds without crossing a "
                    "monotone split; leaf values keep their creation-time "
                    "(clipped, quantized) outputs."
                )
        # out-of-core spill regime (docs round 12): the binned matrix is
        # NOT device-resident — training routes to the chunk-streamed
        # grower (ops/treegrow_ooc.py), whose envelope is the strict
        # grower's core (numerical + categorical, bagging, max_depth).
        # Features that need the whole matrix (or a grower outside the
        # mirror) raise here rather than silently train something else.
        self._ooc_spill = bool(getattr(train_set, "ooc_spill", False))
        if self._ooc_spill:
            mc_l = list(self.cfg.monotone_constraints or [])
            blocked = {
                "monotone_constraints": any(int(c) != 0 for c in mc_l),
                "interaction_constraints": bool(
                    self.cfg.interaction_constraints),
                "forcedsplits_filename": bool(self.cfg.forcedsplits_filename),
                "cegb penalties": any(
                    p != 0 for p in
                    (self.cfg.cegb_penalty_feature_coupled or [])
                    + (self.cfg.cegb_penalty_feature_lazy or [])),
                "linear_tree": bool(self.cfg.linear_tree),
                "extra_trees / feature_fraction_bynode": bool(
                    self.cfg.extra_trees
                    or self.cfg.feature_fraction_bynode < 1.0),
                "tree_learner != serial": self.cfg.tree_learner != "serial",
                "boosting = dart": self.cfg.boosting == "dart",
            }
            bad = [k for k, v in blocked.items() if v]
            if bad:
                raise ValueError(
                    "out_of_core spill training (rows > max_rows_in_hbm) "
                    f"does not support: {', '.join(bad)} — raise "
                    "max_rows_in_hbm (resident regime supports everything) "
                    "or drop the option; see ops/treegrow_ooc.py")
            if self.cfg.use_quantized_grad:
                from ..utils.log import log_warning as _lw
                _lw("use_quantized_grad is ignored by the out-of-core "
                    "spill grower; this run trains float (strict-grower "
                    "mirror)")
        self._linear = bool(self.cfg.linear_tree) and self.cfg.tree_learner == "serial"
        if self.cfg.linear_tree and not self._linear:
            log_warning(
                "linear_tree is implemented for tree_learner=serial only; "
                "training proceeds with CONSTANT leaves."
            )
        if self._linear and self.cfg.boosting == "dart":
            log_warning(
                "linear_tree is not supported with boosting=dart (drop/renorm "
                "assumes constant leaves); training with CONSTANT leaves."
            )
            self._linear = False
        if self._linear and self.objective is not None and self.objective.need_renew:
            # reference: Config::CheckParamConflict forbids linear trees with
            # objectives that renew leaf outputs (l1/huber/quantile/mape)
            raise ValueError(
                f"linear_tree is not supported with objective="
                f"{self.objective.name} (leaf-output renewal)"
            )
        if self._linear and getattr(train_set, "raw_device", None) is None:
            raise ValueError(
                "linear_tree requires raw feature values: the Dataset was "
                "constructed without linear_tree in its params (or raw data "
                "was freed). Pass params={'linear_tree': True} to Dataset."
            )
        if self.cfg.use_quantized_grad and not (self._use_fast or self._use_fast_dp):
            log_warning(
                "use_quantized_grad is implemented on the rounds grower "
                "(tree_growth_mode=rounds / auto-on-TPU) only; this run "
                "trains UNQUANTIZED on the strict grower."
            )
        # distributed tree learner over the device mesh (reference:
        # TreeLearner::CreateTreeLearner picking {serial,data,feature,voting})
        self._dp = None
        self._fp = None
        self._dp_hier = None
        self._dp2d = None
        if self.cfg.tree_learner in ("data", "feature", "voting",
                                     "feature2d"):
            import jax as _jax

            if _jax.device_count() > 1:
                from ..parallel.mesh import make_mesh

                # resident out_of_core datasets never hold host bins; the
                # sharded learners split a host copy once (spill regime is
                # already gated to tree_learner=serial above)
                host_bins = train_set._host_bins(
                    f"tree_learner={self.cfg.tree_learner}")
                mesh = make_mesh()
                if self.cfg.tree_learner == "feature2d":
                    # 2-D (feature, row) mesh for the wide-F regime
                    # (docs/DISTRIBUTED.md "2-D sharding"): d_f feature
                    # blocks x d_r row shards.  A d_f that does not
                    # divide the device count falls back to the
                    # single-level row mesh, loudly, instead of crashing.
                    nd = _jax.device_count()
                    d_f = max(int(self.cfg.num_feature_shards), 1)
                    if d_f > 1 and nd % d_f:
                        log_warning(
                            f"num_feature_shards={d_f} does not divide "
                            f"{nd} devices; training on the single-level "
                            "row mesh")
                        d_f = 1
                    if d_f > 1:
                        from ..parallel.feature2d import Sharded2DData
                        from ..parallel.mesh import make_mesh_2d

                        self._dp2d = Sharded2DData(
                            make_mesh_2d(nd // d_f, d_f),
                            np.asarray(host_bins),
                            np.asarray(
                                train_set.binner.num_bins_per_feature),
                            np.asarray(
                                train_set.binner.missing_bin_per_feature),
                        )
                    else:
                        from ..parallel.data_parallel import ShardedData

                        self._dp = ShardedData(
                            mesh,
                            np.asarray(host_bins),
                            np.asarray(
                                train_set.binner.num_bins_per_feature),
                            np.asarray(
                                train_set.binner.missing_bin_per_feature),
                        )
                elif self.cfg.tree_learner == "feature":
                    from ..parallel.feature_parallel import FeatureShardedData

                    self._fp = FeatureShardedData(
                        mesh,
                        np.asarray(host_bins),
                        np.asarray(train_set.binner.num_bins_per_feature),
                        np.asarray(train_set.binner.missing_bin_per_feature),
                    )
                else:
                    from ..parallel.data_parallel import ShardedData

                    self._pre_partition = (
                        self.cfg.pre_partition and jax.process_count() > 1
                    )
                    self._dp = ShardedData(
                        mesh,
                        np.asarray(host_bins),
                        np.asarray(train_set.binner.num_bins_per_feature),
                        np.asarray(train_set.binner.missing_bin_per_feature),
                        process_local=self._pre_partition,
                    )
                    # nested (dcn, ici) mesh for multi-slice scale-out
                    # (docs/DISTRIBUTED.md "Hierarchical merge"): built
                    # NEXT TO the flat mesh — the hierarchical two-level
                    # merge serves the windowed fused round; every other
                    # grower keeps the single-level path above
                    ns = int(self.cfg.num_slices)
                    if ns > 1:
                        if self._pre_partition:
                            log_warning(
                                "num_slices > 1 is not wired through the "
                                "multi-controller pre_partition path yet; "
                                "training on the single-level mesh")
                        elif _jax.device_count() % ns:
                            log_warning(
                                f"num_slices={ns} does not divide "
                                f"{_jax.device_count()} devices; training "
                                "on the single-level mesh")
                        else:
                            from ..parallel.hierarchy import SlicedData
                            from ..parallel.mesh import (
                                make_mesh_hierarchical)

                            # reshard the flat layout's device buffers —
                            # the nested row layout places the same
                            # per-device blocks, so the bin matrix stays
                            # ONE device copy
                            self._dp_hier = SlicedData.from_sharded(
                                make_mesh_hierarchical(ns), self._dp)

    def reset_split_params(self) -> None:
        """Refresh jit-static split hyperparams after a config mutation
        (reference: GBDT::ResetConfig via reset_parameter callbacks)."""
        self._split_params = SplitParams(
            lambda_l1=self.cfg.lambda_l1,
            lambda_l2=self.cfg.lambda_l2,
            min_data_in_leaf=self.cfg.min_data_in_leaf,
            min_sum_hessian_in_leaf=self.cfg.min_sum_hessian_in_leaf,
            min_gain_to_split=self.cfg.min_gain_to_split,
            max_delta_step=self.cfg.max_delta_step,
            path_smooth=self.cfg.path_smooth,
            cat_l2=self.cfg.cat_l2,
            cat_smooth=self.cfg.cat_smooth,
            max_cat_threshold=self.cfg.max_cat_threshold,
            max_cat_to_onehot=self.cfg.max_cat_to_onehot,
            feature_fraction_bynode=self.cfg.feature_fraction_bynode,
            extra_trees=bool(self.cfg.extra_trees),
            monotone_penalty=self.cfg.monotone_penalty,
            cegb_tradeoff=self.cfg.cegb_tradeoff,
            cegb_penalty_split=self.cfg.cegb_penalty_split,
        )
        # the fused step bakes SplitParams plus several other config fields
        # as traced constants — but learning_rate is a runtime argument, so
        # the common reset_parameter(learning_rate=...) schedule must NOT
        # retrace every iteration; invalidate only when a baked constant
        # really changed (reference: GBDT::ResetConfig propagates num_leaves
        # etc. to the tree learner)
        if getattr(self, "_fused_key", None) != self._fused_bake_key():
            self._fused_step = None
            # a changed baked constant yields a fresh trace, so a previous
            # compile failure no longer applies — give fused another chance
            self._fused_disabled = False
            # the fused predict+convert entry bakes objective constants
            # (e.g. cfg.sigmoid) as traced constants too
            self._convert_entry = None

    def add_valid(self, valid_set, name: str) -> None:
        valid_set.construct(reference=self.train_set)
        self.valid_sets.append(valid_set)
        self.valid_names.append(name)
        n = valid_set.num_data()
        k = self.num_tree_per_iteration
        shape = (n,) if k == 1 else (n, k)
        init = np.zeros(shape, dtype=np.float32)
        if self.init_scores and any(s != 0.0 for s in self.init_scores):
            init += np.asarray(self.init_scores, dtype=np.float32) if k > 1 else np.float32(self.init_scores[0])
        if valid_set.init_score is not None:
            init += np.asarray(valid_set.init_score, dtype=np.float32).reshape(shape)
        # replay existing trees (continued training)
        score = jnp.asarray(init)
        for i, tree in enumerate(self.models):
            c = i % k
            if tree.is_linear:
                vals = jnp.asarray(
                    tree.predict_batch(np.asarray(valid_set.raw_device)),
                    jnp.float32,
                )
            else:
                leaf = valid_set.predict_leaf_binned_tree(tree)
                vals = jnp.asarray(tree.leaf_value, jnp.float32)[leaf]
            if k == 1:
                score = score + vals
            else:
                score = score.at[:, c].add(vals)
        self._valid_scores.append(score)

    # ------------------------------------------------------------------
    def _bagging_mask(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Row selection for this iteration: (mask bool, weights f32).

        reference: BaggingSampleStrategy (bagging.hpp) & GOSSStrategy
        (goss.hpp) via SampleStrategy::CreateSampleStrategy."""
        n = self.train_set.num_data()
        cfg = self.cfg
        if cfg.data_sample_strategy == "goss" or cfg.boosting == "goss":
            return self._goss_mask()
        use_bagging = cfg.bagging_freq > 0 and (
            cfg.bagging_fraction < 1.0
            or cfg.pos_bagging_fraction < 1.0
            or cfg.neg_bagging_fraction < 1.0
        )
        if not use_bagging:
            if self._nobag_cache is None or self._nobag_cache[0].shape[0] != n:
                self._nobag_cache = (
                    jnp.ones((n,), dtype=bool), jnp.ones((n,), jnp.float32)
                )
            return self._nobag_cache
        if self._last_mask is not None and (self.iter_ % cfg.bagging_freq) != 0:
            # re-bag only every bagging_freq iterations (reference: bagging.hpp)
            return self._last_mask
        rng = np.random.RandomState(cfg.bagging_seed + self.iter_)
        qb = getattr(self.train_set, "query_boundaries", None)
        if cfg.bagging_by_query and qb is not None:
            # reference: bagging.hpp bagging_by_query — whole queries are
            # sampled so ranking pairs never straddle the in-bag boundary
            qb = np.asarray(qb)
            nq = len(qb) - 1
            qmask = rng.rand(nq) < cfg.bagging_fraction
            mask = np.repeat(qmask, np.diff(qb))
            out = (jnp.asarray(mask), jnp.ones((n,), jnp.float32))
            self._last_mask = out
            return out
        if cfg.pos_bagging_fraction < 1.0 or cfg.neg_bagging_fraction < 1.0:
            lbl = np.asarray(self.train_set.label)
            mask = np.zeros(n, dtype=bool)
            pos = lbl > 0
            mask[pos] = rng.rand(int(pos.sum())) < cfg.pos_bagging_fraction
            mask[~pos] = rng.rand(int((~pos).sum())) < cfg.neg_bagging_fraction
        else:
            mask = rng.rand(n) < cfg.bagging_fraction
        out = (jnp.asarray(mask), jnp.ones((n,), jnp.float32))
        self._last_mask = out
        return out

    def _goss_mask(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """GOSS (reference: goss.hpp): keep top `top_rate` rows by
        |grad*hess|, sample `other_rate` of the rest and amplify them by
        (1-top_rate)/other_rate.  First 1/learning_rate iterations use the
        full data (reference warm-up rule)."""
        n = self.train_set.num_data()
        cfg = self.cfg
        warmup = int(1.0 / max(cfg.learning_rate, 1e-12))
        if self.iter_ < warmup:
            return jnp.ones((n,), bool), jnp.ones((n,), jnp.float32)
        g, h = self._cur_grad, self._cur_hess
        score_abs = jnp.abs(g * h)
        if score_abs.ndim > 1:
            score_abs = jnp.sum(score_abs, axis=1)
        top_k = max(int(n * cfg.top_rate), 1)
        other_k = max(int(n * cfg.other_rate), 1)
        thresh = jnp.sort(score_abs)[-top_k]
        top_mask = score_abs >= thresh
        rng_key = jax.random.PRNGKey(cfg.bagging_seed + self.iter_)
        u = jax.random.uniform(rng_key, (n,))
        rest_prob = other_k / jnp.maximum(n - top_k, 1)
        rest_mask = (~top_mask) & (u < rest_prob)
        mask = top_mask | rest_mask
        amp = (1.0 - cfg.top_rate) / cfg.other_rate
        weights = jnp.where(rest_mask, amp, 1.0).astype(jnp.float32)
        return mask, weights

    def _feature_mask(self) -> jnp.ndarray:
        """reference: ColSampler::ResetByTree (col_sampler.hpp)."""
        f = self.train_set.num_feature()
        frac = self.cfg.feature_fraction
        if frac >= 1.0:
            return self._allowed_features
        rng = np.random.RandomState(self.cfg.feature_fraction_seed + self.iter_)
        k = max(int(np.ceil(f * frac)), 1)
        chosen = rng.choice(f, size=k, replace=False)
        mask = np.zeros(f, dtype=bool)
        mask[chosen] = True
        return jnp.asarray(mask) & self._allowed_features

    def _use_windowed(self, ts) -> bool:
        """Wide-regime windowed grower gate (ops/treegrow_windowed.py).

        The windowed grower shrinks each histogram pass from full-N to the
        round's small-children window (pass ~200 ms -> ~30 ms at Epsilon,
        400k x 2000 x 255 bins).  Round 7 fused its two per-round phases
        into ONE donated dispatch with zero blocking host syncs (the round
        driver no longer pulls between admit and pass; window sizes are
        predicted from the device's own bound and verified on device), and
        moved the row partition to the Pallas segment kernel — targeting
        the ~0.10-0.14 s/round admit fixed cost that round 6 measured as
        the parity blocker (docs/NEXT.md lever 1).  Still OPT-IN via
        windowed_growth=true until the fused round is re-benched on chip
        (docs/PERF_NOTES.md round 7).  Its v1 feature envelope excludes
        the rarer options below; anything outside falls back to the
        full-pass rounds grower, which supports everything.

        Round 16: inside the windowed envelope, the round MEGAKERNEL
        (ops/round_pallas.py — one HBM sweep of the bin matrix per
        round) is the default round body wherever the Pallas hot path
        runs; the ``megakernel`` extra param / ``LGBMTPU_MEGAKERNEL``
        env ("auto"/"1"/"interpret"/"0") select it, and configurations
        outside ITS envelope (EFB bundles, per-node feature sampling)
        fall back to the three-pass round loudly
        (megakernel_envelope_fallbacks_total + a megakernel_fallback
        event), never silently."""
        return (
            self._on_tpu
            and bool(self.cfg.extra.get("windowed_growth", False))
            and jax.device_count() == 1
            and ts.num_feature() >= 512
            and self.cfg.num_leaves >= 64
            and self._monotone is None
            and self._interaction_sets is None
            and self._forced_schedule() is None
            and self._cegb_lazy is None
            and self._cegb_coupled is None
            and not self._linear
        )

    def _use_windowed_dp(self, ts) -> bool:
        """Sharded fused windowed round gate (docs/DISTRIBUTED.md "Sharded
        fused rounds"): the one-dispatch windowed round over the ICI mesh,
        with the histogram merge a single in-dispatch psum/psum_scatter
        (parallel/data_parallel.py::grow_tree_windowed_data_parallel).
        Mirrors :meth:`_use_windowed`'s envelope minus the single-device
        requirement; configurations outside it fall back to the
        multi-dispatch sharded rounds grower (fast-DP) or the strict
        sharded grower, which support everything.  EFB is excluded (the
        bundled tables are not threaded through the sharded path yet)."""
        mode = self.cfg.tree_growth_mode
        return (
            self._on_tpu
            and bool(self.cfg.extra.get("windowed_growth", False))
            and (self._dp is not None or self._dp2d is not None)
            and self.cfg.tree_learner in ("data", "voting", "feature2d")
            and (mode == "rounds" or (mode == "auto" and self._on_tpu))
            and getattr(ts, "efb", None) is None
            and ts.num_feature() >= 512
            and self.cfg.num_leaves >= 64
            and self._monotone is None
            and self._interaction_sets is None
            and self._forced_schedule() is None
            and self._cegb_lazy is None
            and self._cegb_coupled is None
            and not self._linear
        )

    def _use_windowed_hier(self, ts) -> bool:
        """Multi-slice hierarchical merge gate (docs/DISTRIBUTED.md
        "Hierarchical merge"): the two-level windowed round over the
        nested (dcn, ici) mesh — intra-slice psum/psum_scatter, top-k
        feature exchange over dcn.  Rides :meth:`_use_windowed_dp`'s
        envelope, minus per-node feature sampling (the slice-local vote
        must be deterministic and slice-consistent)."""
        return (
            self._dp_hier is not None
            and not self._needs_node_rng
            and self._use_windowed_dp(ts)
        )

    def _use_windowed_2d(self, ts) -> bool:
        """2-D (feature, row) mesh gate (docs/DISTRIBUTED.md "2-D
        sharding"): the one-dispatch windowed round with the bin matrix
        on P(feature, row) — feature-complete per-block histograms, the
        owned-feature election over the feature axis.  Rides
        :meth:`_use_windowed_dp`'s envelope minus per-node feature
        sampling (the owned-feature search needs the sampled set to span
        the full axis deterministically, like the scatter merge)."""
        return (
            self._dp2d is not None
            and not self._needs_node_rng
            and self._use_windowed_dp(ts)
        )

    def _windowed_dp_merge(self) -> str:
        """Merge strategy for the sharded fused round: tree_learner=voting
        maps to the owned-feature ``psum_scatter`` variant (the reference's
        ReduceScatter + per-rank feature ownership — half the merge bytes,
        split search parallelized over F), tree_learner=data to the plain
        ``psum`` (replicated split search, the latency-lean ICI default).
        Per-node feature sampling forces psum: under owned features each
        rank would sample only its block (see
        grow_tree_windowed_data_parallel)."""
        if self.cfg.tree_learner == "voting" and not self._needs_node_rng:
            return "scatter"
        return "psum"

    @property
    def _monotone_method(self) -> str:
        """Effective monotone method for the growers: 'advanced' downgrades
        to 'intermediate' (reference: LeafConstraintsBase::Create; the
        advanced cost-based refinement is descoped, warned at setup)."""
        if self._monotone is None:
            return "basic"
        return ("intermediate"
                if self.cfg.monotone_constraints_method
                in ("intermediate", "advanced") else "basic")

    def _leaf_tile(self, ts, use_efb: bool = True) -> int:
        quant = bool(self.cfg.use_quantized_grad)
        if ts.max_num_bins <= 64 and self._on_tpu:
            # XLA einsum strategy (ops/histogram.py) — no Mosaic VMEM
            # ceiling.  Measured: 8 is best at 31 leaves (pass cost grows
            # with lanes); deep trees amortize per-round fixed costs, so
            # go wider once rounds are leaf-count-bound.
            tile = 16 if self.cfg.num_leaves > 63 else 8
            return max(1, min(tile, self.cfg.num_leaves))
        f_eff = (
            ts.efb.num_bundled
            if use_efb and getattr(ts, "efb", None) is not None
            else ts.num_feature()
        )
        # channel-aware tile selection lives with the kernel cost model
        # (ops/hist_pallas.py::recommended_leaf_tile): ~60-lane budgets,
        # narrow tile16-bf16 / tile20-q16, wide 10-f32 / 20-q
        from ..ops.hist_pallas import recommended_leaf_tile

        return recommended_leaf_tile(
            ts.max_num_bins, f_eff, self.cfg.num_leaves,
            hist_precision=self.cfg.hist_precision, quantized=quant)

    _last_mask = None
    _nobag_cache = None
    _fused_step = None
    _report_finish_every_iter = False
    _finish_probe = None

    _pre_partition = False
    _cegb_lazy = None
    _cegb_lazy_used = None
    _fused_disabled = False
    _ooc_spill = False
    _convert_entry = None

    def _localize_tree(self, arrays, leaf_id_pad):
        """Multi-controller runs: bring the (replicated) tree and the
        (row-sharded) leaf ids back to process-local arrays so the host-side
        boosting state — scores, gradients, metrics — stays local, exactly
        like the reference keeps per-rank state local while only the tree
        learner communicates (reference: DataParallelTreeLearner)."""
        if jax.process_count() <= 1:
            return arrays, leaf_id_pad
        arrays = jax.tree.map(lambda a: jnp.asarray(np.asarray(a)), arrays)
        if self._pre_partition and self._dp is not None:
            # each rank keeps only ITS rows' leaf ids (pre_partition: no
            # rank ever holds the full row space)
            leaf_id_pad = jnp.asarray(self._dp.local_rows(leaf_id_pad))
        else:
            from jax.experimental import multihost_utils

            leaf_id_pad = jnp.asarray(
                multihost_utils.process_allgather(leaf_id_pad, tiled=True)
            )
        return arrays, leaf_id_pad

    def _fused_eligible(self, grad) -> bool:
        """The common hot path — single-class fast grower with a built-in
        objective and no per-iteration host work — can run gradients + tree
        + score update in ONE jit dispatch (the axon tunnel costs ~1-1.5 ms
        per dispatch, ~16 ms/iter across the unfused ~12 dispatches)."""
        return (
            grad is None
            and self.cfg.fused_training
            and not self._fused_disabled
            and not self._ooc_spill  # bins are streamed, not traced inputs
            # each class tree inlines into the trace: cap the blowup
            and self.num_tree_per_iteration <= 8
            # very wide/deep shapes compile the combined trace pathologically
            # (observed: 255 leaves x 2000 features never finished); the
            # unfused path costs only ~16 ms/iter extra dispatch overhead,
            # noise at shapes this slow per-iteration anyway
            and self.cfg.num_leaves * self.train_set.num_feature() <= 100_000
            and self._use_fast
            and self._fp is None
            and self._dp is None
            and not self._linear
            and self.objective is not None
            and not self.objective.need_renew
            and self.objective.is_fusable()
            and self._cegb_coupled is None
            # lazy charges carry (N, F) state across iterations — kept on
            # the unfused loop rather than threading it through the step
            and self._cegb_lazy is None
            and not self._needs_node_rng
            and not self.cfg.use_quantized_grad
        )

    @property
    def _is_goss(self) -> bool:
        return self.cfg.data_sample_strategy == "goss" or self.cfg.boosting == "goss"

    _forced_cache = None

    def _forced_schedule(self):
        """Parse forcedsplits_filename into a (leaf, feature, bin) schedule
        for the strict grower (reference: SerialTreeLearner::ForceSplits —
        the JSON tree prefix is applied BFS before gain-driven growth;
        thresholds map to bins through the train binner)."""
        if not self.cfg.forcedsplits_filename:
            return None
        if self._forced_cache is not None:
            return self._forced_cache
        import json as _json
        from collections import deque

        with open(self.cfg.forcedsplits_filename) as fh:
            root = _json.load(fh)
        leaves, feats, bins_ = [], [], []
        # BFS with the grower's leaf numbering: left child keeps the parent's
        # leaf id; the right child of the s-th split gets leaf id s+1
        queue = deque([(root, 0)])
        step = 0
        while queue:
            node, leaf = queue.popleft()
            fidx = int(node["feature"])
            thr = float(node["threshold"])
            mapper = self.binner.mappers[fidx]
            # bin containing the threshold: value <= upper_bound semantics
            b = int(mapper.transform(np.asarray([thr]))[0])
            leaves.append(leaf)
            feats.append(fidx)
            bins_.append(b)
            right_leaf = step + 1
            if "left" in node and node["left"]:
                queue.append((node["left"], leaf))
            if "right" in node and node["right"]:
                queue.append((node["right"], right_leaf))
            step += 1
        self._forced_cache = (
            jnp.asarray(leaves, jnp.int32),
            jnp.asarray(feats, jnp.int32),
            jnp.asarray(bins_, jnp.int32),
            len(leaves),
        )
        return self._forced_cache

    def _fused_bake_key(self):
        """Every config field the fused trace bakes as a constant.  Must stay
        in sync with _get_fused_step/grow_kwargs: a field listed here forces
        a retrace on reset_parameter; a missing field is silently frozen."""
        ts = self.train_set
        return (
            self._split_params,
            self.cfg.sigmoid,
            self.cfg.num_leaves,
            self.cfg.max_depth,
            self.cfg.hist_precision,
            self._leaf_tile(ts) if ts is not None else None,
            self._is_goss,
            self.cfg.top_rate,
            self.cfg.other_rate,
            self.cfg.forcedsplits_filename,
            self._monotone_method,
        )

    def _get_fused_step(self):
        if self._fused_step is not None:
            return self._fused_step
        self._fused_key = self._fused_bake_key()  # baked into the trace below
        ts = self.train_set
        obj = self.objective
        label, weight = self._label, self._weight
        bins = ts.bins_device
        nbpf, mbpf = ts.num_bins_pf_device, ts.missing_bin_pf_device
        cat_mask, mono = self._categorical_mask, self._monotone
        contri = self._feature_contri
        inter = self._interaction_sets
        efb_tabs = ts.efb_device_tables() if getattr(ts, "efb", None) is not None else None
        bins_t = ts.bins_device_t() if self._on_tpu else None
        from ..ops.treegrow_fast import grow_tree_fast

        fs = self._forced_schedule()
        grow_kwargs = dict(
            num_leaves=self.cfg.num_leaves,
            num_bins=ts.max_num_bins,
            max_depth=self.cfg.max_depth,
            params=self._split_params,
            leaf_tile=self._leaf_tile(ts),
            hist_precision=self.cfg.hist_precision,
            use_pallas=self._on_tpu,
            # entries past num_leaves-1 can never apply; clamping avoids
            # unrolling dead traced rounds
            n_forced=(min(fs[3], self.cfg.num_leaves - 1) if fs else 0),
            monotone_method=self._monotone_method,
        )

        use_goss = self._is_goss
        n_rows = ts.num_data()
        top_rate, other_rate = self.cfg.top_rate, self.cfg.other_rate
        k = self.num_tree_per_iteration

        @jax.jit
        # jaxlint: disable=R2 (cached in self._fused_step; rebuilt only when _fused_bake_key changes)
        def step(score, row_mask, sample_weight, feature_mask, shrinkage,
                 goss_key, goss_warm, obj_state):
            g, h, new_obj_state = obj.fused_gradients(
                score, label, weight, obj_state)
            if use_goss:
                # GOSS in-trace (reference: goss.hpp): the mask depends on
                # THIS iteration's gradients, so it must live inside the
                # fused step; goss_warm (traced bool) selects the full-data
                # warm-up behavior without retracing
                score_abs = jnp.abs(g * h)
                if score_abs.ndim > 1:
                    score_abs = jnp.sum(score_abs, axis=1)
                top_k = max(int(n_rows * top_rate), 1)
                other_k = max(int(n_rows * other_rate), 1)
                thresh = jnp.sort(score_abs)[-top_k]
                top_mask = score_abs >= thresh
                u = jax.random.uniform(goss_key, (n_rows,))
                rest_prob = other_k / jnp.maximum(n_rows - top_k, 1)
                rest_mask = (~top_mask) & (u < rest_prob)
                amp = (1.0 - top_rate) / other_rate
                row_mask = jnp.where(goss_warm, row_mask, top_mask | rest_mask)
                sample_weight = jnp.where(
                    goss_warm, sample_weight,
                    jnp.where(rest_mask, amp, 1.0).astype(jnp.float32),
                )
            arrays_all, leaf_all = [], []
            new_score = score
            for c in range(k):  # k static: multiclass trees inline in-trace
                gc = g if k == 1 else g[:, c]
                hc = h if k == 1 else h[:, c]
                arrays, leaf_id = grow_tree_fast(
                    bins, gc, hc, row_mask, sample_weight, feature_mask,
                    nbpf, mbpf, cat_mask, mono, inter, None, None, None,
                    efb_tabs[0] if efb_tabs else None,
                    efb_tabs[1] if efb_tabs else None,
                    efb_tabs[2] if efb_tabs else None,
                    bins_t,
                    contri,
                    fs[0] if fs else None,
                    fs[1] if fs else None,
                    fs[2] if fs else None,
                    **grow_kwargs,
                )
                row_delta = (arrays.leaf_value * shrinkage)[leaf_id]
                if k == 1:
                    new_score = new_score + row_delta
                else:
                    new_score = new_score.at[:, c].add(row_delta)
                arrays_all.append(arrays)
                leaf_all.append(leaf_id)
            return (tuple(arrays_all), tuple(leaf_all), new_score, g, h,
                    new_obj_state)

        self._fused_step = step
        return step

    # ------------------------------------------------------------------
    def train_one_iter(self, grad: Optional[np.ndarray] = None, hess: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration (reference: GBDT::TrainOneIter).  Returns
        True when training cannot continue (all trees constant).

        The telemetry wrapper around :meth:`_train_one_iter_impl` emits the
        per-round training summary (docs/OBSERVABILITY.md): one
        ``boost_round`` event carrying the round's dispatch/sync/compile
        deltas read from the sanitizer's host-side ledger — deliberately NO
        wall-clock delta, because the fast path dispatches asynchronously
        and an unsynced timer would be the jaxlint-R9 mistiming
        anti-pattern.  The ``boost_round`` SPAN around the impl carries the
        same ledger deltas; its duration is host-causal by design (spans
        never add a sync — jaxlint R10), and with LGBMTPU_JAX_PROFILER=1
        it mirrors into jax.profiler.StepTraceAnnotation so profiler steps
        line up with boosting iterations."""
        if not _obs.enabled():
            return self._train_one_iter_impl(grad, hess)
        c0 = _san.compile_totals()
        with _trace.span("boost_round", iteration=self.iter_) as sp:
            finished = self._train_one_iter_impl(grad, hess)
            c1 = _san.compile_totals()
            sp.set(dispatches=c1["dispatches"] - c0["dispatches"],
                   host_syncs=c1["host_syncs"] - c0["host_syncs"],
                   compiles=c1["compiles"] - c0["compiles"])
        _obs.counter("train_boost_rounds_total").inc()
        _obs.event("boost_round", iteration=self.iter_,
                   dispatches=c1["dispatches"] - c0["dispatches"],
                   host_syncs=c1["host_syncs"] - c0["host_syncs"],
                   compiles=c1["compiles"] - c0["compiles"],
                   traces=c1["traces"] - c0["traces"])
        return finished

    def _train_one_iter_impl(self, grad: Optional[np.ndarray] = None, hess: Optional[np.ndarray] = None) -> bool:
        ts = self.train_set
        k = self.num_tree_per_iteration
        if self._fused_eligible(grad):
            if self._is_goss:
                # masks computed in-trace; pass full-data placeholders
                if self._nobag_cache is None or self._nobag_cache[0].shape[0] != ts.num_data():
                    self._nobag_cache = (
                        jnp.ones((ts.num_data(),), bool),
                        jnp.ones((ts.num_data(),), jnp.float32),
                    )
                row_mask, sample_weight = self._nobag_cache
                goss_key = jax.random.PRNGKey(self.cfg.bagging_seed + self.iter_)
                warmup = int(1.0 / max(self.cfg.learning_rate, 1e-12))
                goss_warm = jnp.asarray(self.iter_ < warmup)
            else:
                row_mask, sample_weight = self._bagging_mask()
                goss_key = jax.random.PRNGKey(0)
                goss_warm = jnp.asarray(False)
            feature_mask = self._feature_mask()
            shrinkage = 1.0 if self.average_output else self.cfg.learning_rate
            step = self._get_fused_step()
            try:
                arrays_all, leaf_all, self._score, g, h, obj_state = step(
                    self._score, row_mask, sample_weight,
                    jnp.asarray(feature_mask), jnp.float32(shrinkage),
                    goss_key, goss_warm, self.objective.fused_state(),
                )
            except Exception:  # noqa: BLE001
                from ..utils.log import log_warning

                try:
                    # transient transport hiccups are common on the remote
                    # compile path: retry once before giving up
                    arrays_all, leaf_all, self._score, g, h, obj_state = step(
                        self._score, row_mask, sample_weight,
                        jnp.asarray(feature_mask), jnp.float32(shrinkage),
                        goss_key, goss_warm, self.objective.fused_state(),
                    )
                except Exception as e:  # noqa: BLE001
                    # nothing is mutated before `step` returns, so fall back
                    # to the unfused path (re-enabled if reset_parameter
                    # changes a baked constant and retraces)
                    log_warning(
                        "fused training step failed twice "
                        f"({type(e).__name__}: {str(e)[:200]}); "
                        "falling back to per-phase dispatches"
                    )
                    self._fused_disabled = True
                    self._fused_step = None
                    # recurse into the impl: the telemetry wrapper already
                    # opened this round's ledger window (one event per round)
                    return self._train_one_iter_impl(grad, hess)
            self.objective.set_fused_state(obj_state)
            self._cur_grad, self._cur_hess = g, h
            for c, arrays in enumerate(arrays_all):
                self._guard_accumulate(arrays)
                self._pending.append((arrays, shrinkage, None))
                for vi, vs in enumerate(self.valid_sets):
                    from ..ops.treegrow_fast import predict_leaf_arrays

                    leaf_v = predict_leaf_arrays(
                        arrays, vs.bins_device, ts.missing_bin_pf_device,
                    )
                    vals = (arrays.leaf_value * jnp.float32(shrinkage))[leaf_v]
                    if k == 1:
                        self._valid_scores[vi] = self._valid_scores[vi] + vals
                    else:
                        self._valid_scores[vi] = self._valid_scores[vi].at[:, c].add(vals)
            self.iter_ += 1
            self._invalidate_pred_cache("train_one_iter")
            if self._report_finish_every_iter:
                # C API path: the reference reports is_finished immediately.
                # Reading THIS iteration's num_leaves would sync the tunnel
                # (~23 ms) and stall the async pipeline, so probe the
                # PREVIOUS iteration's trees — by now their step has retired,
                # making the read ~free; is_finished lags one iteration.
                prev = self._finish_probe
                self._finish_probe = (
                    self.iter_,
                    tuple(a.num_leaves for a in arrays_all),
                )
                for x in self._finish_probe[1]:
                    getattr(x, "copy_to_host_async", lambda: None)()
                # only trust a probe from the immediately preceding iteration
                # (rollback / reset / interleaved unfused iterations stale it)
                if prev is not None and prev[0] == self.iter_ - 1:
                    return all(int(np.asarray(x)) <= 1 for x in prev[1])
                return False
            if (self.iter_ % 32) == 0:
                # library path: syncing every iteration is too expensive (see
                # above); a finished model only accretes constant trees, so a
                # deferred check is safe — it is documented in engine.train.
                # The non-finite guard piggybacks on the same sync cadence.
                self._guard_check()
                return all(bool(a.num_leaves <= 1) for a in arrays_all)
            return False
        if grad is None:
            g, h = self.objective.get_gradients(self._score, self._label, self._weight)
        else:
            g = jnp.asarray(grad, jnp.float32).reshape(self._score.shape)
            h = jnp.asarray(hess, jnp.float32).reshape(self._score.shape)
        # fault-injection sites: poison one gradient/hessian element at a
        # chosen iteration to drive the non-finite guard-rail tests
        # (utils/faults.py; no-ops unless LGBMTPU_FAULT arms them)
        g = _faults.corrupt_nonfinite("nonfinite_grad", self.iter_ + 1, g)
        h = _faults.corrupt_nonfinite("nonfinite_hess", self.iter_ + 1, h)
        self._cur_grad, self._cur_hess = g, h
        row_mask, sample_weight = self._bagging_mask()
        feature_mask = self._feature_mask()

        all_const = True
        for c in range(k):
            # recomputed per class tree: a feature used by an earlier class's
            # tree this iteration is no longer charged (reference: cegb.hpp
            # updates coupled state sequentially across trees)
            cegb_pen = None
            if self._cegb_coupled is not None:
                cegb_pen = jnp.where(self._cegb_used_global, 0.0, self._cegb_coupled)
            gc = g if k == 1 else g[:, c]
            hc = h if k == 1 else h[:, c]
            node_rng = (
                jax.random.PRNGKey(self.cfg.extra_seed + self.iter_ * 131 + c)
                if self._needs_node_rng else None
            )
            if self._ooc_spill:
                # out-of-core spill: the binned matrix streams through the
                # chunked grower (a strict-grower mirror — bitwise on the
                # scatter strategy, ops/treegrow_ooc.py)
                from ..ops.treegrow_ooc import grow_tree_ooc

                arrays, leaf_id = grow_tree_ooc(
                    ts.ooc_chunk_iter,
                    ts.num_data(),
                    ts.num_feature(),
                    jnp.asarray(gc, jnp.float32),
                    jnp.asarray(hc, jnp.float32),
                    jnp.asarray(row_mask, bool),
                    jnp.asarray(sample_weight, jnp.float32),
                    jnp.asarray(feature_mask, bool),
                    ts.num_bins_pf_device,
                    ts.missing_bin_pf_device,
                    self._categorical_mask,
                    num_leaves=self.cfg.num_leaves,
                    num_bins=ts.max_num_bins,
                    max_depth=self.cfg.max_depth,
                    params=self._split_params,
                    chunk_rows=ts.ooc_chunk_rows,
                )
            elif self._fp is not None:
                from ..parallel.feature_parallel import grow_tree_feature_parallel

                arrays, leaf_id = grow_tree_feature_parallel(
                    self._fp,
                    jnp.asarray(gc, jnp.float32),
                    jnp.asarray(hc, jnp.float32),
                    jnp.asarray(row_mask, bool),
                    jnp.asarray(sample_weight, jnp.float32),
                    np.asarray(feature_mask, bool),  # jaxlint: disable=R1 (feature_mask is a host numpy mask; the FP learner pads+shards host-side, no device pull)
                    self._categorical_mask,
                    self._monotone,
                    self._interaction_sets,
                    node_rng,
                    self._feature_contri,
                    num_leaves=self.cfg.num_leaves,
                    num_bins=ts.max_num_bins,
                    max_depth=self.cfg.max_depth,
                    params=self._split_params,
                    monotone_method=self._monotone_method,
                )
                arrays, leaf_id = self._localize_tree(arrays, leaf_id)
            elif self._dp2d is not None and self._use_windowed_2d(ts):
                # 2-D (feature, row) mesh (docs/DISTRIBUTED.md "2-D
                # sharding"): each device owns an (F/d_f, N/d_r) tile,
                # the histogram phase crosses the feature axis with ZERO
                # collectives, the owned-feature election crosses it with
                # scalars + one (N_loc,) decision broadcast — all inside
                # the one donated dispatch per round
                from ..parallel.feature2d import grow_tree_windowed_feature2d

                d2 = self._dp2d
                quant = self.cfg.use_quantized_grad
                arrays, leaf_id_pad = grow_tree_windowed_feature2d(
                    d2,
                    d2.pad_rows_device(gc, jnp.float32),
                    d2.pad_rows_device(hc, jnp.float32),
                    d2.pad_rows_device(row_mask, bool, fill=False),
                    d2.pad_rows_device(sample_weight, jnp.float32,
                                       fill=1.0),
                    feature_mask,
                    self._categorical_mask,
                    None,  # rng_key: per-node sampling is outside the gate
                    (jax.random.PRNGKey(
                        self.cfg.seed * 1000003 + self.iter_ * 31 + c)
                     if quant else None),
                    self._feature_contri,
                    num_leaves=self.cfg.num_leaves,
                    num_bins=ts.max_num_bins,
                    max_depth=self.cfg.max_depth,
                    params=self._split_params,
                    leaf_tile=self._leaf_tile(ts, use_efb=False),
                    hist_precision=self.cfg.hist_precision,
                    use_pallas=self._on_tpu,
                    quantize_bins=(self.cfg.num_grad_quant_bins
                                   if quant else 0),
                    stochastic_rounding=bool(self.cfg.stochastic_rounding),
                    quant_renew=bool(self.cfg.quant_train_renew_leaf),
                    guard_label=(
                        f" (boosting iteration {self.iter_ + 1})"),
                )
                arrays, leaf_id_pad = self._localize_tree(
                    arrays, leaf_id_pad)
                leaf_id = leaf_id_pad[: ts.num_data()]
            elif self._dp_hier is not None and self._use_windowed_hier(ts):
                # multi-slice scale-out (docs/DISTRIBUTED.md "Hierarchical
                # merge"): the two-level windowed round — intra-slice
                # psum/psum_scatter over ici unchanged, top-k feature
                # exchange over dcn, all inside the one donated dispatch
                from ..parallel.hierarchy import (
                    grow_tree_windowed_hierarchical)

                dph = self._dp_hier
                quant = self.cfg.use_quantized_grad
                arrays, leaf_id_pad = grow_tree_windowed_hierarchical(
                    dph,
                    dph.pad_rows_device(gc, jnp.float32),
                    dph.pad_rows_device(hc, jnp.float32),
                    dph.pad_rows_device(row_mask, bool, fill=False),
                    dph.pad_rows_device(sample_weight, jnp.float32,
                                        fill=1.0),
                    feature_mask,
                    self._categorical_mask,
                    (jax.random.PRNGKey(
                        self.cfg.seed * 1000003 + self.iter_ * 31 + c)
                     if quant else None),
                    self._feature_contri,
                    num_leaves=self.cfg.num_leaves,
                    num_bins=ts.max_num_bins,
                    max_depth=self.cfg.max_depth,
                    params=self._split_params,
                    leaf_tile=self._leaf_tile(ts, use_efb=False),
                    hist_precision=self.cfg.hist_precision,
                    use_pallas=self._on_tpu,
                    quantize_bins=(self.cfg.num_grad_quant_bins
                                   if quant else 0),
                    stochastic_rounding=bool(self.cfg.stochastic_rounding),
                    quant_renew=bool(self.cfg.quant_train_renew_leaf),
                    merge=self._windowed_dp_merge(),
                    top_k_features=int(self.cfg.top_k_features),
                    guard_label=(
                        f" (boosting iteration {self.iter_ + 1})"),
                )
                arrays, leaf_id_pad = self._localize_tree(
                    arrays, leaf_id_pad)
                leaf_id = leaf_id_pad[: ts.num_data()]
            elif self._dp is not None and self._use_windowed_dp(ts):
                # the tentpole path: sharded one-dispatch windowed rounds —
                # histogram merge is one psum/psum_scatter INSIDE the
                # donated dispatch, 1 dispatch + 0 blocking syncs per rank
                from ..parallel.data_parallel import (
                    grow_tree_windowed_data_parallel)

                dp = self._dp
                quant = self.cfg.use_quantized_grad
                arrays, leaf_id_pad = grow_tree_windowed_data_parallel(
                    dp,
                    dp.pad_rows_device(gc, jnp.float32),
                    dp.pad_rows_device(hc, jnp.float32),
                    dp.pad_rows_device(row_mask, bool, fill=False),
                    dp.pad_rows_device(sample_weight, jnp.float32, fill=1.0),
                    feature_mask,
                    self._categorical_mask,
                    node_rng,
                    (jax.random.PRNGKey(self.cfg.seed * 1000003 + self.iter_ * 31 + c)
                     if quant else None),
                    self._feature_contri,
                    num_leaves=self.cfg.num_leaves,
                    num_bins=ts.max_num_bins,
                    max_depth=self.cfg.max_depth,
                    params=self._split_params,
                    leaf_tile=self._leaf_tile(ts, use_efb=False),
                    hist_precision=self.cfg.hist_precision,
                    use_pallas=self._on_tpu,
                    quantize_bins=(self.cfg.num_grad_quant_bins if quant else 0),
                    stochastic_rounding=bool(self.cfg.stochastic_rounding),
                    quant_renew=bool(self.cfg.quant_train_renew_leaf),
                    merge=self._windowed_dp_merge(),
                    guard_label=f" (boosting iteration {self.iter_ + 1})",
                    megakernel_opt=self.cfg.extra.get("megakernel"),
                )
                arrays, leaf_id_pad = self._localize_tree(arrays, leaf_id_pad)
                leaf_id = leaf_id_pad[: ts.num_data()]
            elif self._dp is not None and self._use_fast_dp:
                from ..parallel.data_parallel import grow_tree_fast_data_parallel

                dp = self._dp
                quant = self.cfg.use_quantized_grad
                arrays, leaf_id_pad = grow_tree_fast_data_parallel(
                    dp,
                    dp.pad_rows_device(gc, jnp.float32),
                    dp.pad_rows_device(hc, jnp.float32),
                    dp.pad_rows_device(row_mask, bool, fill=False),
                    dp.pad_rows_device(sample_weight, jnp.float32, fill=1.0),
                    feature_mask,
                    self._categorical_mask,
                    self._monotone,
                    self._interaction_sets,
                    node_rng,
                    (jax.random.PRNGKey(self.cfg.seed * 1000003 + self.iter_ * 31 + c)
                     if quant else None),
                    cegb_pen,
                    self._feature_contri,
                    num_leaves=self.cfg.num_leaves,
                    num_bins=ts.max_num_bins,
                    max_depth=self.cfg.max_depth,
                    params=self._split_params,
                    leaf_tile=self._leaf_tile(ts, use_efb=False),
                    hist_precision=self.cfg.hist_precision,
                    use_pallas=self._on_tpu,
                    quantize_bins=(self.cfg.num_grad_quant_bins if quant else 0),
                    stochastic_rounding=bool(self.cfg.stochastic_rounding),
                    quant_renew=bool(self.cfg.quant_train_renew_leaf),
                    track_path=self._linear,
                    monotone_method=self._monotone_method,
                )
                arrays, leaf_id_pad = self._localize_tree(arrays, leaf_id_pad)
                leaf_id = leaf_id_pad[: ts.num_data()]
            elif self._dp is not None:
                from ..parallel.data_parallel import grow_tree_data_parallel

                dp = self._dp
                arrays, leaf_id_pad = grow_tree_data_parallel(
                    dp,
                    dp.pad_rows_device(gc, jnp.float32),
                    dp.pad_rows_device(hc, jnp.float32),
                    dp.pad_rows_device(row_mask, bool, fill=False),
                    dp.pad_rows_device(sample_weight, jnp.float32, fill=1.0),
                    feature_mask,
                    self._categorical_mask,
                    self._monotone,
                    self._interaction_sets,
                    node_rng,
                    self._feature_contri,
                    num_leaves=self.cfg.num_leaves,
                    num_bins=ts.max_num_bins,
                    max_depth=self.cfg.max_depth,
                    params=self._split_params,
                    parallel_mode=("voting" if self.cfg.tree_learner == "voting" else "data"),
                    top_k=self.cfg.top_k,
                    monotone_method=self._monotone_method,
                )
                arrays, leaf_id_pad = self._localize_tree(arrays, leaf_id_pad)
                leaf_id = leaf_id_pad[: ts.num_data()]
            elif self._use_fast and self._use_windowed(ts):
                from ..ops.treegrow_windowed import grow_tree_windowed

                quant = self.cfg.use_quantized_grad
                efb_tabs_w = (ts.efb_device_tables()
                              if getattr(ts, "efb", None) is not None else None)
                arrays, leaf_id = grow_tree_windowed(
                    ts.bins_device_t(),
                    gc,
                    hc,
                    row_mask,
                    sample_weight,
                    feature_mask,
                    ts.num_bins_pf_device,
                    ts.missing_bin_pf_device,
                    node_rng,
                    (jax.random.PRNGKey(self.cfg.seed * 1000003 + self.iter_ * 31 + c)
                     if quant else None),
                    self._feature_contri,
                    self._categorical_mask,
                    ts.efb_bins_device_t() if getattr(ts, "efb", None) is not None else None,
                    efb_tabs_w[1] if efb_tabs_w else None,
                    efb_tabs_w[2] if efb_tabs_w else None,
                    num_leaves=self.cfg.num_leaves,
                    num_bins=ts.max_num_bins,
                    max_depth=self.cfg.max_depth,
                    params=self._split_params,
                    leaf_tile=self._leaf_tile(ts),
                    hist_precision=self.cfg.hist_precision,
                    use_pallas=self._on_tpu,
                    quantize_bins=(self.cfg.num_grad_quant_bins if quant else 0),
                    stochastic_rounding=bool(self.cfg.stochastic_rounding),
                    quant_renew=bool(self.cfg.quant_train_renew_leaf),
                    guard_label=f" (boosting iteration {self.iter_ + 1})",
                    megakernel_opt=self.cfg.extra.get("megakernel"),
                )
            elif self._use_fast:
                from ..ops.treegrow_fast import grow_tree_fast

                quant = self.cfg.use_quantized_grad
                efb_tabs = ts.efb_device_tables() if getattr(ts, "efb", None) is not None else None
                fs = self._forced_schedule()
                grow_out = grow_tree_fast(
                    ts.bins_device,
                    gc,
                    hc,
                    row_mask,
                    sample_weight,
                    feature_mask,
                    ts.num_bins_pf_device,
                    ts.missing_bin_pf_device,
                    self._categorical_mask,
                    self._monotone,
                    self._interaction_sets,
                    node_rng,
                    (jax.random.PRNGKey(self.cfg.seed * 1000003 + self.iter_ * 31 + c)
                     if quant else None),
                    cegb_pen,
                    efb_tabs[0] if efb_tabs else None,
                    efb_tabs[1] if efb_tabs else None,
                    efb_tabs[2] if efb_tabs else None,
                    ts.bins_device_t() if self._on_tpu else None,
                    self._feature_contri,
                    fs[0] if fs else None,
                    fs[1] if fs else None,
                    fs[2] if fs else None,
                    self._cegb_lazy,
                    self._cegb_lazy_used,
                    n_forced=(min(fs[3], self.cfg.num_leaves - 1) if fs else 0),
                    num_leaves=self.cfg.num_leaves,
                    num_bins=ts.max_num_bins,
                    max_depth=self.cfg.max_depth,
                    params=self._split_params,
                    # measured on-chip (bench.py sweep): 8 leaves/pass is
                    # the optimum — wider payload lanes slow the Mosaic
                    # kernel more than the saved admission rounds buy.
                    # Wide datasets cap further: the Mosaic toolchain rejects
                    # kernels whose output tensor F_pad*lanes*B*4 exceeds
                    # ~100MB (measured), so Epsilon-shape runs use fewer
                    # leaves per pass.
                    leaf_tile=self._leaf_tile(ts),
                    hist_precision=self.cfg.hist_precision,
                    use_pallas=self._on_tpu,
                    quantize_bins=(self.cfg.num_grad_quant_bins if quant else 0),
                    stochastic_rounding=bool(self.cfg.stochastic_rounding),
                    quant_renew=bool(self.cfg.quant_train_renew_leaf),
                    track_path=self._linear,
                    monotone_method=self._monotone_method,
                )
                if self._cegb_lazy is not None and len(grow_out) == 3:
                    arrays, leaf_id, self._cegb_lazy_used = grow_out
                else:
                    arrays, leaf_id = grow_out
            else:
                fs = self._forced_schedule()
                grow_out = grow_tree(
                    ts.bins_device,
                    gc,
                    hc,
                    row_mask,
                    sample_weight,
                    feature_mask,
                    ts.num_bins_pf_device,
                    ts.missing_bin_pf_device,
                    self._categorical_mask,
                    self._monotone,
                    self._interaction_sets,
                    node_rng,
                    cegb_pen,
                    self._cegb_lazy,
                    self._cegb_lazy_used,
                    fs[0] if fs else None,
                    fs[1] if fs else None,
                    fs[2] if fs else None,
                    self._feature_contri,
                    num_leaves=self.cfg.num_leaves,
                    num_bins=ts.max_num_bins,
                    max_depth=self.cfg.max_depth,
                    params=self._split_params,
                    hist_strategy="auto",
                    track_path=self._linear,
                    n_forced=(fs[3] if fs else 0),
                    monotone_method=self._monotone_method,
                )
                if self._cegb_lazy is not None and len(grow_out) == 3:
                    arrays, leaf_id, self._cegb_lazy_used = grow_out
                else:
                    arrays, leaf_id = grow_out
            self._guard_accumulate(arrays)
            linear_fit = None
            if self._linear and arrays.path_features is not None:
                from ..ops.linear import fit_linear_leaves

                used_path = arrays.path_features
                if self._categorical_mask is not None:
                    used_path = used_path & ~self._categorical_mask[None, :]
                coef, const, fidx, nf, lin_pred, _good = fit_linear_leaves(
                    ts.raw_device, leaf_id,
                    gc * sample_weight, hc * sample_weight, row_mask,
                    used_path, arrays.leaf_value,
                    jnp.float32(self.cfg.linear_lambda),
                    # cap on path features per leaf model (reference fits
                    # ALL path features; 24 covers any tree this package
                    # grows at default depths — deeper paths are truncated
                    # to the lowest-indexed features)
                    K=min(24, ts.num_feature()),
                    num_leaves=self.cfg.num_leaves,
                )
                linear_fit = (coef, const, fidx, nf)
            if self._cegb_coupled is not None:
                valid_nodes = (
                    jnp.arange(self.cfg.num_leaves - 1) < arrays.num_leaves - 1
                )
                self._cegb_used_global = self._cegb_used_global.at[
                    jnp.where(valid_nodes, arrays.split_feature, 2 * self.cfg.num_leaves + self._cegb_used_global.shape[0])
                ].set(True, mode="drop")
            leaf_values = arrays.leaf_value
            if self.objective is not None and self.objective.need_renew:
                renewed = self.objective.renew_tree_output(
                    None, self._label, self._weight,
                    self._score if k == 1 else self._score[:, c],
                    leaf_id, self.cfg.num_leaves,
                )
                if renewed is not None:
                    active = jnp.arange(self.cfg.num_leaves) < arrays.num_leaves
                    leaf_values = jnp.where(active, renewed, 0.0)
                    arrays = arrays._replace(leaf_value=leaf_values)
            if self._use_fast:
                # async path: no host materialization — score/valid updates
                # run on device from the TreeArrays; the host Tree is built
                # lazily (self.models property) so iterations pipeline freely
                shrinkage = 1.0 if self.average_output else self.cfg.learning_rate
                all_const = jnp.logical_and(
                    jnp.asarray(all_const, dtype=bool), arrays.num_leaves <= 1
                )
                self._pending.append((arrays, shrinkage, linear_fit))
                if linear_fit is not None:
                    row_delta = lin_pred * jnp.float32(shrinkage)
                else:
                    row_delta = (arrays.leaf_value * jnp.float32(shrinkage))[leaf_id]
                if k == 1:
                    self._score = self._score + row_delta
                else:
                    self._score = self._score.at[:, c].add(row_delta)
                for vi, vs in enumerate(self.valid_sets):
                    from ..ops.treegrow_fast import predict_leaf_arrays

                    leaf_v = predict_leaf_arrays(
                        arrays, vs.bins_device, ts.missing_bin_pf_device,
                    )
                    if linear_fit is not None:
                        from ..ops.linear import predict_linear_rows

                        vals = predict_linear_rows(
                            vs.raw_device, leaf_v, coef, const, fidx, nf,
                            arrays.leaf_value,
                        ) * jnp.float32(shrinkage)
                    else:
                        vals = (arrays.leaf_value * jnp.float32(shrinkage))[leaf_v]
                    if k == 1:
                        self._valid_scores[vi] = self._valid_scores[vi] + vals
                    else:
                        self._valid_scores[vi] = self._valid_scores[vi].at[:, c].add(vals)
                continue
            tree = tree_from_device(arrays, self.binner, linear=linear_fit)
            if tree.num_leaves > 1:
                all_const = False
            # RF (average_output) takes unscaled deltas regardless of which
            # alias ("rf"/"random_forest") selected the mode
            shrinkage = 1.0 if self.average_output else self.cfg.learning_rate
            tree.apply_shrinkage(shrinkage)
            # Trees hold PURE deltas during training; the boost_from_average
            # init score lives in self.init_scores and is folded into tree 0
            # only at serialization time (_trees_for_export), so valid-score
            # updates, rollback, DART rescaling and continued training all
            # treat trees uniformly (reference folds via Tree::AddBias; we
            # fold at save to keep the .txt model self-contained).
            dev_leaf_vals = jnp.asarray(tree.leaf_value, jnp.float32)
            pad = self.cfg.num_leaves - dev_leaf_vals.shape[0]
            if pad > 0:
                dev_leaf_vals = jnp.concatenate([dev_leaf_vals, jnp.zeros(pad, jnp.float32)])
            delta = dev_leaf_vals
            if linear_fit is not None:
                row_delta = lin_pred * jnp.float32(tree.shrinkage)
            else:
                row_delta = delta[leaf_id]
            if k == 1:
                self._score = self._score + row_delta
            else:
                self._score = self._score.at[:, c].add(row_delta)
            self.models.append(tree)  # jaxlint: disable=L3 (append+version-bump protocol: the pack key carries (version, len) so a mid-build append is caught at insert; locking here would nest the models-property device flush under the pack lock — an L2)
            # valid scores
            for vi, vs in enumerate(self.valid_sets):
                leaf_v = vs.predict_leaf_binned_tree(tree)
                if linear_fit is not None:
                    from ..ops.linear import predict_linear_rows

                    vals = predict_linear_rows(
                        vs.raw_device, jnp.asarray(leaf_v), coef, const, fidx, nf,
                        arrays.leaf_value,
                    ) * jnp.float32(tree.shrinkage)
                else:
                    vals = jnp.asarray(tree.leaf_value, jnp.float32)[leaf_v]
                if k == 1:
                    self._valid_scores[vi] = self._valid_scores[vi] + vals
                else:
                    self._valid_scores[vi] = self._valid_scores[vi].at[:, c].add(vals)
        self.iter_ += 1
        self._invalidate_pred_cache("train_one_iter")
        if not isinstance(all_const, bool):
            # fast path: only force the cannot-split flag to host every 32
            # iterations, so callers doing `if train_one_iter(): break` don't
            # serialize the pipeline.  The reference stops the moment a
            # constant tree appears; we detect within 32 iterations (once an
            # iteration is constant the score stops changing, so every later
            # iteration is constant too and the next check catches it).
            if (self.iter_ % 32) == 0:
                self._guard_check()
                return bool(all_const)
            return False
        return all_const

    def rollback_one_iter(self) -> None:
        """reference: GBDT::RollbackOneIter.  The tree-list pops and the
        version bump share one pack-lock section (round 19): a serving
        pack build racing the rollback retries at insert time instead of
        caching a half-popped ensemble under the pre-rollback version."""
        if self.iter_ <= 0:
            return
        with self._plock():
            self._rollback_one_iter_locked()

    def _rollback_one_iter_locked(self) -> None:
        k = self.num_tree_per_iteration
        for c in reversed(range(k)):
            tree = self.models.pop()
            if tree.is_linear:
                vals = jnp.asarray(
                    tree.predict_batch(np.asarray(self.train_set.raw_device)),  # jaxlint: disable=L2 (rollback is a mutator: the pop + score rebuild must be atomic vs serving pack builds, and the linear-path pull is trainer-thread-only)
                    jnp.float32,
                )
            else:
                leaf_id = self.train_set.predict_leaf_binned_tree(tree)
                vals = jnp.asarray(tree.leaf_value, jnp.float32)[leaf_id]
            if k == 1:
                self._score = self._score - vals
            else:
                self._score = self._score.at[:, c].add(-vals)
            for vi, vs in enumerate(self.valid_sets):
                leaf_v = vs.predict_leaf_binned_tree(tree)
                vv = jnp.asarray(tree.leaf_value, jnp.float32)[leaf_v]
                if k == 1:
                    self._valid_scores[vi] = self._valid_scores[vi] - vv
                else:
                    self._valid_scores[vi] = self._valid_scores[vi].at[:, c].add(-vv)
        self.iter_ -= 1
        self._invalidate_pred_cache("rollback_one_iter")

    # ------------------------------------------------------------------
    def _converted(self, score: jnp.ndarray) -> np.ndarray:
        if self.objective is not None:
            return np.asarray(self.objective.convert_output(score))
        return np.asarray(score)

    def _eval_margin(self, score: jnp.ndarray) -> jnp.ndarray:
        """Margin used for metric evaluation; RF averages (scores accumulate
        raw sums during training)."""
        return score

    _eval_jit_cache = None

    def _device_evaluator(self, data_idx: int, ds, dev_metrics):
        """One jit per eval set covering every device-capable metric
        (reference: the CUDA build's device metric reductions,
        src/metric/cuda/cuda_pointwise_metric.cu).  convert_output runs
        in-trace; only len(dev_metrics) scalars cross to the host."""
        if self._eval_jit_cache is None:
            self._eval_jit_cache = {}
        key = (data_idx, tuple(type(m) for m in dev_metrics), ds.weight is None)
        hit = self._eval_jit_cache.get(key)
        if hit is not None:
            return hit
        obj = self.objective
        if data_idx == 0 and self._label is not None:
            # the training labels/weights already live on device
            label_dev, weight_dev = self._label, self._weight
        else:
            label_dev = jnp.asarray(np.asarray(ds.label))
            weight_dev = None if ds.weight is None else jnp.asarray(
                np.asarray(ds.weight), jnp.float32
            )
        # rank metrics need the eval set's padded query layout + ideal DCGs
        # (host-precomputed per dataset, device constants in the trace);
        # the layout is computed once and shared by every rank metric
        shared = None
        if any(m.needs_queries for m in dev_metrics):
            from ..metrics import pad_queries

            pad_idx_np, pad_mask_np = pad_queries(ds.query_boundaries)
            shared = {
                "pad_idx_np": pad_idx_np, "pad_mask_np": pad_mask_np,
                "pad_idx": jnp.asarray(pad_idx_np),
                "pad_mask": jnp.asarray(pad_mask_np),
            }
        qconsts = {
            id(m): m.device_query_constants(
                np.asarray(ds.label), ds.query_boundaries, shared)
            for m in dev_metrics if m.needs_queries
        }

        @jax.jit
        # jaxlint: disable=R2 (cached in self._eval_jit_cache keyed by (data_idx, metric set))
        def run(margin, label, weight):
            pred = obj.convert_output(margin) if obj is not None else margin
            outs = []
            for m in dev_metrics:
                if m.needs_queries:
                    outs.append(jnp.asarray(
                        m.device_eval_queries(pred, qconsts[id(m)]),
                        jnp.float32))
                else:
                    outs.append(jnp.asarray(
                        m.device_eval(pred, label, weight),
                        jnp.float32).reshape(-1))
            return jnp.concatenate(outs)

        entry = (run, label_dev, weight_dev)
        self._eval_jit_cache[key] = entry
        return entry

    def _eval_target(self, data_idx: int):
        """data_idx -> (dataset, raw score, display name); 0 = train,
        i>0 = (i-1)-th valid set."""
        if data_idx == 0:
            return self.train_set, self._score, self.train_name
        return (self.valid_sets[data_idx - 1],
                self._valid_scores[data_idx - 1],
                self.valid_names[data_idx - 1])

    def _eval_at_synced(self, data_idx: int) -> List[Tuple[str, str, float, bool]]:
        """Distributed eval under pre_partition: each rank holds only its
        row shard, so metric values must sync across processes (reference:
        Metric::Eval + Network::GlobalSyncUpBySum).  Decomposable metrics
        sum local (numerator, denominator) pairs; the AUC family gathers
        shard predictions and evaluates globally on every rank."""
        from ..basic import _allgather_rows_f64 as gather

        ds, score, name = self._eval_target(data_idx)
        pred = self._converted(self._eval_margin(score))
        label = np.asarray(ds.label)
        weight = None if ds.weight is None else np.asarray(ds.weight)
        qb = ds.query_boundaries

        per_metric = [(m, m.eval_sums(pred, label, weight, qb))
                      for m in self.metrics]
        sum_rows = [(num, den) for _, s in per_metric if s is not None
                    for (_, num, den, _) in s]
        totals = None
        if sum_rows:
            loc = np.ascontiguousarray(np.asarray(sum_rows, np.float64))
            totals = gather(loc.reshape(1, -1)).reshape(
                -1, len(sum_rows), 2).sum(axis=0)
        gathered = None
        out: List[Tuple[str, str, float, bool]] = []
        i = 0
        for m, s in per_metric:
            if s is not None:
                for (mn, _, _, hib) in s:
                    num_g, den_g = totals[i]
                    out.append((name, mn,
                                m.transform(num_g / max(den_g, 1e-300)), hib))
                    i += 1
            else:
                if gathered is None:
                    gathered = (
                        gather(pred),
                        gather(label),
                        None if weight is None else gather(weight),
                    )
                for (mn, v, hib) in m.eval(*gathered, None):
                    out.append((name, mn, v, hib))
        return out

    def eval_at(self, data_idx: int) -> List[Tuple[str, str, float, bool]]:
        """data_idx 0 = training, 1.. = valid sets (reference: GBDT::GetEvalAt).
        Returns (dataset_name, metric_name, value, is_higher_better)."""
        # eval pulls metric scalars anyway — piggyback the non-finite
        # guard so runs with valid sets detect corruption within a round
        self._guard_check()
        if self._pre_partition and jax.process_count() > 1:
            return self._eval_at_synced(data_idx)
        ds, score, name = self._eval_target(data_idx)
        k = self.num_tree_per_iteration
        dev_metrics = [
            m for m in self.metrics
            if self.objective is not None and m.supports_device(k)
            and (not m.needs_queries or ds.query_boundaries is not None)
        ]
        host_metrics = [m for m in self.metrics if m not in dev_metrics]
        out_by_metric = {}
        if dev_metrics:
            run, label_dev, weight_dev = self._device_evaluator(
                data_idx, ds, dev_metrics
            )
            vals = np.asarray(run(self._eval_margin(score), label_dev, weight_dev))
            off = 0
            for m in dev_metrics:
                if m.needs_queries:
                    names = m.device_out_names()
                else:
                    names = [m.name]
                out_by_metric[id(m)] = [
                    (nm, m.transform(float(vals[off + j])), m.is_higher_better)
                    for j, nm in enumerate(names)
                ]
                off += len(names)
        if host_metrics:
            pred = self._converted(self._eval_margin(score))
            label = np.asarray(ds.label)
            weight = None if ds.weight is None else np.asarray(ds.weight)
            for m in host_metrics:
                out_by_metric[id(m)] = m.eval(
                    pred, label, weight, ds.query_boundaries
                )
        out = []
        for m in self.metrics:  # preserve configured metric order
            for mn, v, hib in out_by_metric[id(m)]:
                out.append((name, mn, v, hib))
        return out

    # ------------------------------------------------------------------
    def _stacked(self, start: int = 0, num_iteration: int = -1, trees=None):
        k = self.num_tree_per_iteration
        if trees is None:
            trees = self.models
            lo = start * k
            hi = len(trees) if num_iteration < 0 else min((start + num_iteration) * k, len(trees))
            trees = trees[lo:hi]
        if not trees:
            return None
        max_l = max(max((t.num_leaves for t in trees), default=1), 2)
        m = max_l - 1
        T = len(trees)

        def pad(get, dtype, width, fill=0):
            out = np.full((T, width), fill, dtype=dtype)
            for i, t in enumerate(trees):
                a = get(t)
                out[i, : len(a)] = a
            return jnp.asarray(out)

        out = dict(
            split_feature=pad(lambda t: t.split_feature, np.int32, m),
            threshold=pad(lambda t: _f32_threshold_upper(t.threshold), np.float32, m),
            default_left=pad(lambda t: t.default_left(), bool, m),
            missing_type=pad(
                lambda t: (t.decision_type.astype(np.int32) >> 2) & 3, np.int32, m
            ),
            left_child=pad(lambda t: t.left_child, np.int32, m, fill=-1),
            right_child=pad(lambda t: t.right_child, np.int32, m, fill=-1),
            num_leaves=jnp.asarray([t.num_leaves for t in trees], jnp.int32),
            leaf_value=pad(lambda t: t.leaf_value, np.float32, max_l),
            k=k,
            T=T,
        )
        if any(t.num_cat > 0 for t in trees):
            # flat bitset words + per-node (offset, word-count) so the device
            # traversal can do Tree::CategoricalDecision with two gathers
            is_cat_np = np.zeros((T, m), bool)
            base_np = np.zeros((T, m), np.int32)
            nw_np = np.zeros((T, m), np.int32)
            words = []
            off = 0
            for i, t in enumerate(trees):
                icm = np.asarray(t.is_categorical_node(), bool)
                is_cat_np[i, : len(icm)] = icm
                for ndx in np.nonzero(icm)[0]:
                    ci = int(t.threshold[ndx])
                    lo = int(t.cat_boundaries[ci])
                    hi = int(t.cat_boundaries[ci + 1])
                    base_np[i, ndx] = off + lo
                    nw_np[i, ndx] = hi - lo
                w = np.asarray(t.cat_threshold, np.uint32)
                words.append(w)
                off += len(w)
            out["is_cat"] = jnp.asarray(is_cat_np)
            out["cat_base"] = jnp.asarray(base_np)
            out["cat_nwords"] = jnp.asarray(nw_np)
            out["cat_words"] = jnp.asarray(
                np.concatenate(words) if off else np.zeros(1, np.uint32))
        return out

    # -- packed-ensemble serving cache (round 9; versioned round 18) ---
    _PACKED_CACHE_CAP = 32  # bounds early-stop chunk windows etc.
    # versions retained after a mutation: the current one plus the
    # previous (in-flight serving readers of the pre-mutation pack) —
    # older versions are evicted by _invalidate_pred_cache, counted in
    # predict_stale_pack_evictions_total
    _PACKED_KEEP_VERSIONS = 2

    def _packed(self, start: int = 0, num_iteration: int = -1, *,
                pad_trees_to: int = 0):
        """Device-resident packed ensemble for serving: the `_stacked` SoA
        arrays built once per (version, tree range, model state) and
        cached, so a warm ``predict`` performs ZERO host-side re-pack and
        re-upload.

        The cache lives in ``self._pred_cache`` (None = empty).  Every
        model mutation (train_one_iter, rollback_one_iter, the ``models``
        setter, Booster.refit/shuffle_models, the C-API leaf refits)
        BUMPS ``_pack_version`` instead of nulling the dict
        (_invalidate_pred_cache), so the key's leading version component
        makes stale entries unreachable while the previous version stays
        servable for in-flight serving readers — and the key additionally
        carries ``len(self.models)`` as a belt-and-braces guard.

        ``pad_trees_to`` pads the tree axis with single-leaf zero-value
        trees to a multiple of that window so the early-stop chunk op runs
        every chunk through one executable.  Packed entries also carry:

        * ``_trees``: the export-form host trees (linear path, scale)
        * ``_linear``: True when any tree has linear leaves (host walk)
        """
        k = self.num_tree_per_iteration
        races = 0
        while True:
            # lookup UNDER the pack lock (shared with
            # _invalidate_pred_cache — round 19): a trainer-thread bump
            # cannot evict entries mid-lookup or race the key's version
            # component
            if races >= 3:
                # a sustained mutation cadence (e.g. a set_leaf_output
                # loop) must not starve a serving build forever: after a
                # few lost races, build UNDER the lock — mutators wait
                # one build instead of the reader retrying unboundedly
                with self._plock():
                    return self._packed_build_locked(start, num_iteration,
                                                     pad_trees_to)
            with self._plock():
                v0 = self._pack_version
                n_models = len(self.models)  # flushes pending device trees
                lo = start * k
                hi = n_models if num_iteration < 0 else min(
                    (start + num_iteration) * k, n_models)
                key = (v0, lo, hi, n_models, pad_trees_to)
                if self._pred_cache is None:
                    self._pred_cache = {}
                hit = self._pred_cache.get(key)
                if hit is not None:
                    _obs.counter("predict_packed_cache_hits_total").inc()
                    return hit
                _obs.counter("predict_packed_cache_misses_total").inc()
            # build OUTSIDE the lock (host re-pack + device uploads must
            # not stall concurrent serving lookups of resident versions)
            trees = self._trees_for_export(start, num_iteration)
            pack_trees = trees
            if pad_trees_to and trees:
                pad = (-len(trees)) % pad_trees_to
                pack_trees = trees + [_dummy_tree()] * pad
            s = self._stacked(trees=pack_trees) if pack_trees else None
            if s is not None:
                s["_trees"] = trees
                s["_linear"] = any(t.is_linear for t in trees)
            with self._plock():
                if self._pack_version != v0:
                    # a mutation landed mid-build: the freshly packed
                    # arrays may reflect post-mutation trees, so caching
                    # them under the pre-mutation version would hand
                    # in-flight readers a torn pack — rebuild under the
                    # new version instead
                    _obs.counter("predict_pack_build_races_total").inc()
                    races += 1
                    continue
                if len(self._pred_cache) >= self._PACKED_CACHE_CAP:
                    self._pred_cache.pop(next(iter(self._pred_cache)))
                self._pred_cache[key] = s
                return s

    def _packed_build_locked(self, start: int, num_iteration: int,
                             pad_trees_to: int):
        """The starvation fallback: one full lookup+build+insert with the
        pack lock HELD — no mutation can interleave, so progress is
        guaranteed after repeated build races (callers: _packed only)."""
        k = self.num_tree_per_iteration
        n_models = len(self.models)
        lo = start * k
        hi = n_models if num_iteration < 0 else min(
            (start + num_iteration) * k, n_models)
        key = (self._pack_version, lo, hi, n_models, pad_trees_to)
        if self._pred_cache is None:
            self._pred_cache = {}
        hit = self._pred_cache.get(key)
        if hit is not None:
            _obs.counter("predict_packed_cache_hits_total").inc()
            return hit
        _obs.counter("predict_packed_cache_misses_total").inc()
        trees = self._trees_for_export(start, num_iteration)
        pack_trees = trees
        if pad_trees_to and trees:
            pad = (-len(trees)) % pad_trees_to
            pack_trees = trees + [_dummy_tree()] * pad
        s = self._stacked(trees=pack_trees) if pack_trees else None
        if s is not None:
            s["_trees"] = trees
            s["_linear"] = any(t.is_linear for t in trees)
        if len(self._pred_cache) >= self._PACKED_CACHE_CAP:
            self._pred_cache.pop(next(iter(self._pred_cache)))
        self._pred_cache[key] = s
        return s

    # -- serving telemetry (docs/OBSERVABILITY.md) ---------------------
    @staticmethod
    def _serve_t0() -> Tuple[float, int]:
        """(wall clock, compile count) opening a serving entry's telemetry
        window — closed by :meth:`_serve_note` AFTER the entry's accounted
        ``sync_pull``, so the latency reservoir measures the real
        end-to-end call (dispatch + device compute + pull), never the
        async-enqueue time (the jaxlint-R9 mistiming class)."""
        return time.perf_counter(), _san.compile_totals()["compiles"]

    def _serve_note(self, entry: str, n: int, t0c0: Tuple[float, int],
                    bucket: Optional[int] = None,
                    trace_ctx=None) -> None:
        """Record one serving call.  Bucket hit/miss is decided by whether
        the call compiled anything (a miss = a new bucket/shape opened);
        only hits feed the warm-latency reservoirs, so cold compiles never
        pollute the p50/p99 the serving round cares about.  ``bucket``
        (the pow-2 ladder rung the batch padded to) additionally labels a
        per-bucket reservoir — ``predict_warm_latency_ms{bucket="128"}``
        in the Prometheus output — so multi-bucket request mixes stay
        attributable.  The closing timer read is honest by construction:
        every entry calls this AFTER its accounted ``sync_pull``, and the
        retroactive span records the same interval (jaxlint R9/R10)."""
        if not _obs.enabled():
            return
        t0, c0 = t0c0
        dt_ms = (time.perf_counter() - t0) * 1e3
        warm = _san.compile_totals()["compiles"] == c0
        _obs.counter("predict_requests_total").inc()
        _obs.counter("predict_rows_total").inc(n)
        if warm:
            _obs.counter("predict_bucket_hits_total").inc()
            _obs.histogram("predict_warm_latency_ms").observe(dt_ms)
            # per-entry reservoirs are LABEL SETS on the one family
            # (predict_warm_latency_ms{entry="raw"}), not dotted-suffix
            # names — the dotted form rendered as a separate Prometheus
            # family per entry (round-11 infra note, retired round 18)
            _obs.histogram(_obs.labeled(
                "predict_warm_latency_ms", entry=entry)).observe(dt_ms)
            if bucket is not None:
                _obs.histogram(_obs.labeled(
                    "predict_warm_latency_ms", bucket=bucket)).observe(dt_ms)
        else:
            _obs.counter("predict_bucket_misses_total").inc()
        # trace_ctx (when a serving dispatcher passed its leg context)
        # makes the device-side span a CHILD of that dispatch leg — this
        # runs on dispatcher threads whose ambient span stack is empty,
        # so parentage must arrive explicitly (the R21 rule)
        _trace.record_span(f"predict.{entry}", dt_ms / 1e3,
                           parent=trace_ctx, rows=n,
                           bucket=bucket, warm=warm)

    def _pad_rows(self, X: np.ndarray, n_bucket: int) -> jnp.ndarray:
        """(N, F) host batch -> (n_bucket, F) f32 device array, zero-padded
        tail (padding rows are masked on device by the serving ops)."""
        xh = np.zeros((n_bucket, X.shape[1]), dtype=np.float32)
        xh[: X.shape[0]] = X
        return jnp.asarray(xh)

    def _active_mask(self, n: int, n_bucket: int) -> Optional[jnp.ndarray]:
        if n_bucket == n:
            return None
        m = np.zeros(n_bucket, dtype=bool)
        m[:n] = True
        return jnp.asarray(m)

    def predict_raw(self, X: np.ndarray, start_iteration: int = 0, num_iteration: int = -1) -> np.ndarray:
        """Raw margin prediction on raw feature values (device traversal).

        Uses the export representation — init score folded into the first
        tree(s) per class — so an in-memory model and its .txt save/load
        round-trip predict BIT-IDENTICALLY (the reference also folds:
        Tree::AddBias).

        Serving contract (round 9, pinned by tests/test_predict_budget.py):
        a warm call is ONE device dispatch and ONE blocking pull — the
        packed ensemble comes from the `_packed` cache, the batch is padded
        to the `_predict_bucket` ladder so the traversal compiles once per
        bucket, and multiclass reduces all k classes in that same single
        dispatch (predict_ops.predict_raw_multiclass)."""
        s = self._packed(start_iteration, num_iteration)
        n = X.shape[0]
        k = self.num_tree_per_iteration
        if s is None:
            init = np.asarray(self.init_scores, dtype=np.float64)
            base = np.zeros((n, k), dtype=np.float64) + init[None, :]
            return base[:, 0] if k == 1 else base
        trees = s["_trees"]
        if s["_linear"]:
            # linear leaves evaluate per-leaf ridge models on raw features:
            # vectorized host walk
            Xh = np.asarray(X, dtype=np.float64)
            n_per_class = max(len(trees) // k, 1)
            scale = (1.0 / n_per_class) if self.average_output else 1.0
            outs = np.zeros((n, k), dtype=np.float64)
            for i, t in enumerate(trees):
                outs[:, i % k] += t.predict_batch(Xh) * scale
            return outs[:, 0] if k == 1 else outs
        # categorical bitset decisions ride the device traversal too
        # (Tree::CategoricalDecision as two gathers over flat bitset words)
        cat_kw = {}
        if "is_cat" in s:
            cat_kw = dict(cat_words=s["cat_words"])
        t0c0 = self._serve_t0()
        nb = _predict_bucket(n)
        x = self._pad_rows(X, nb)
        active = self._active_mask(n, nb)
        n_per_class = max(s["T"] // k, 1)
        scale = (1.0 / n_per_class) if self.average_output else 1.0
        _san.record_dispatch()
        if k == 1:
            out = predict_ops.predict_raw_values(
                x, s["split_feature"], s["threshold"], s["default_left"],
                s["missing_type"], s["left_child"], s["right_child"],
                s["num_leaves"], s["leaf_value"],
                is_cat=s.get("is_cat"), cat_base=s.get("cat_base"),
                cat_nwords=s.get("cat_nwords"), active=active, **cat_kw,
            )
            res = np.asarray(
                _san.sync_pull(out)[:n], dtype=np.float64) * scale
            self._serve_note("raw", n, t0c0, bucket=nb)
            return res
        # multiclass: ONE class-reshaped dispatch (predict_raw_multiclass)
        # replaced the k-dispatch per-class host loop; outputs are
        # bit-identical (same per-class summation order)
        out = predict_ops.predict_raw_multiclass(
            x, s["split_feature"], s["threshold"], s["default_left"],
            s["missing_type"], s["left_child"], s["right_child"],
            s["num_leaves"], s["leaf_value"],
            is_cat=s.get("is_cat"), cat_base=s.get("cat_base"),
            cat_nwords=s.get("cat_nwords"), active=active, k=k, **cat_kw,
        )
        res = np.asarray(_san.sync_pull(out)[:n], dtype=np.float64) * scale
        self._serve_note("raw_multiclass", n, t0c0, bucket=nb)
        return res

    def predict_raw_sharded(self, X: np.ndarray, mesh,
                            start_iteration: int = 0,
                            num_iteration: int = -1) -> np.ndarray:
        """``predict_raw`` for giant batches: score a row-sharded ``X`` as
        ONE SPMD dispatch over the row axis of ``mesh``.

        Serving contract (pinned by tests/test_predict_budget.py): BITWISE
        equal to the single-device ``predict_raw``, and a warm call is one
        packed-cache hit, ONE dispatch and ONE blocking pull.  N pads to
        ``d_row * _predict_bucket(ceil(N / d_row))`` so every rank sees the
        same per-rank bucket ladder (one compile per bucket per mesh); the
        padded rows are masked on device exactly like the single-device
        ladder.  The replicated per-tree tables are placed on the mesh once
        per (pack, mesh) and cached inside the pack, so warm calls move
        ONLY the row-sharded batch."""
        s = self._packed(start_iteration, num_iteration)
        n = X.shape[0]
        k = self.num_tree_per_iteration
        if s is None or s["_linear"]:
            # nothing traverses on device (init-score-only or host-walked
            # linear leaves) — the single-device path is already optimal
            return self.predict_raw(X, start_iteration, num_iteration)
        from jax.sharding import NamedSharding, PartitionSpec as _P
        from ..parallel.mesh import DATA_AXIS as _AX

        d_r = int(mesh.shape[_AX])
        t0c0 = self._serve_t0()
        nb = d_r * _predict_bucket(max(1, -(-n // d_r)))
        row_s = NamedSharding(mesh, _P(_AX))
        xh = np.zeros((nb, X.shape[1]), dtype=np.float32)
        xh[:n] = X
        x = jax.device_put(xh, row_s)
        am = np.zeros(nb, dtype=bool)
        am[:n] = True
        active = jax.device_put(am, row_s)
        has_cat = "is_cat" in s
        tabs = s.setdefault("_mesh_tables", {}).get(mesh)
        if tabs is None:
            rep_s = NamedSharding(mesh, _P())
            names = ["split_feature", "threshold", "default_left",
                     "missing_type", "left_child", "right_child",
                     "num_leaves", "leaf_value"]
            if has_cat:
                names += ["is_cat", "cat_base", "cat_nwords", "cat_words"]
            tabs = tuple(jax.device_put(s[m], rep_s) for m in names)
            s["_mesh_tables"][mesh] = tabs
        entry = _sharded_raw_entry(mesh, k, has_cat)
        n_per_class = max(s["T"] // k, 1)
        scale = (1.0 / n_per_class) if self.average_output else 1.0
        _san.record_dispatch()
        out = entry(x, active, *tabs)
        res = np.asarray(_san.sync_pull(out)[:n], dtype=np.float64) * scale
        self._serve_note("raw_sharded", n, t0c0, bucket=nb)
        return res

    def _get_convert_entry(self):
        """Jitted traversal + ``objective.convert_output`` in ONE trace:
        a converted warm predict is one dispatch + one accounted pull
        (round 12 — it was 2 dispatches: the raw traversal, then a
        separate convert dispatch over the re-uploaded raw result).
        Cached for the model's lifetime; reset_split_params nulls it when
        a baked objective constant (e.g. ``sigmoid``) changes.  The
        entry's traced IR is pinned by the ``predict_warm_converted``
        audit contract on a real toy booster (analysis/contracts.py) —
        precisely because this jit closes over instance state the AST
        rules cannot follow."""
        if self._convert_entry is not None:
            return self._convert_entry
        obj = self.objective

        @functools.partial(jax.jit, static_argnames=("k",))
        # jaxlint: disable=R2 (cached in self._convert_entry; nulled only when a baked constant changes)
        def run(x, sf, th, dl, mt, lc, rc, nl, lv, is_cat, cat_base,
                cat_nwords, cat_words, active, *, k):
            if k == 1:
                out = predict_ops.predict_raw_values(
                    x, sf, th, dl, mt, lc, rc, nl, lv, is_cat=is_cat,
                    cat_base=cat_base, cat_nwords=cat_nwords,
                    cat_words=cat_words, active=active)
            else:
                out = predict_ops.predict_raw_multiclass(
                    x, sf, th, dl, mt, lc, rc, nl, lv, is_cat=is_cat,
                    cat_base=cat_base, cat_nwords=cat_nwords,
                    cat_words=cat_words, active=active, k=k)
            # conversions are rowwise (sigmoid/exp/softmax): padded rows
            # cannot leak into real ones, so the bucket ladder stays safe
            return obj.convert_output(out)

        self._convert_entry = run
        return run

    def _predict_converted(self, X, start_iteration, num_iteration):
        """Fused converted predict (serving contract: 1 dispatch + 1
        accounted pull, packed-cache hit, bucket ladder).  Returns None
        when the fused entry does not apply (no trees, linear leaves,
        RF averaging — the caller falls back to the 2-dispatch path,
        also reachable via ``LGBMTPU_FUSED_CONVERT=0``)."""
        s = self._packed(start_iteration, num_iteration)
        if s is None or s["_linear"]:
            return None
        n = X.shape[0]
        k = self.num_tree_per_iteration
        t0c0 = self._serve_t0()
        nb = _predict_bucket(n)
        x = self._pad_rows(X, nb)
        active = self._active_mask(n, nb)
        run = self._get_convert_entry()
        _san.record_dispatch()
        out = run(x, s["split_feature"], s["threshold"], s["default_left"],
                  s["missing_type"], s["left_child"], s["right_child"],
                  s["num_leaves"], s["leaf_value"], s.get("is_cat"),
                  s.get("cat_base"), s.get("cat_nwords"), s.get("cat_words"),
                  active, k=k)
        res = np.asarray(_san.sync_pull(out)[:n])
        self._serve_note("converted", n, t0c0, bucket=nb)
        return res

    # -- coalesced serving dispatch (round 18, lightgbm_tpu/serve) ------
    @staticmethod
    def _coalesced_raw_fn(k: int):
        """The raw-path executable a coalesced batch dispatches: the SAME
        module-level jitted traversal the single-caller warm entries use
        (``predict_ops.predict_raw_values`` / ``predict_raw_multiclass``)
        — never a serve-owned jit.  The serving loop therefore reuses the
        bucket ladder's already-compiled executables (zero retraces by
        construction), and the ``predict_coalesced_bucket`` audit
        contract (analysis/contracts.py) traces exactly this function, so
        the coalescer can never silently grow a second executable
        family."""
        return (predict_ops.predict_raw_values if k == 1
                else predict_ops.predict_raw_multiclass)

    def _coalescible(self, raw_score: bool) -> bool:
        """Whether a ``predict(raw_score=)`` call can ride the coalesced
        batch path BITWISE — the same envelope as the single-caller fast
        entries: a packed non-linear ensemble, no prediction
        early-stopping (its per-row tree count is margin-dependent), and
        for converted output the fused-convert conditions (a real
        objective, no RF host-side averaging, escape hatch honored).
        Ineligible models are served per-request through the full
        ``predict`` path by the runtime (still correct, not coalesced)."""
        early = (
            self.cfg.pred_early_stop
            and not self.average_output
            and self.objective is not None
            and getattr(self.objective, "name", "") in (
                "binary", "multiclass", "multiclassova")
        )
        if early:
            return False
        s = self._packed(0, -1)
        if s is None or s["_linear"]:
            return False
        if raw_score or self.objective is None:
            return True
        return (not self.average_output
                and os.environ.get("LGBMTPU_FUSED_CONVERT", "1") != "0")

    def predict_coalesced(self, x, active, n, *, convert: bool,
                          trace_ctx=None):
        """One coalesced serving batch (lightgbm_tpu/serve/runtime.py):
        ``x`` is an ALREADY-STAGED (nb, F) f32 device batch — the
        runtime's pinned-buffer upload, enqueued while the previous batch
        executes — and ``active`` its row mask (None at exact rung fill,
        mirroring ``_active_mask``).  ONE dispatch + ONE accounted sync
        for the whole batch; rows slice back out per request BITWISE
        equal to individual ``predict`` calls (rows traverse
        independently, conversions are rowwise, and the padded result is
        pinned bit-identical to the unpadded one).

        ``convert=False`` returns raw margins ((n,) or (n, k), f64 with
        the RF scale applied exactly as ``predict_raw``); ``convert=True``
        dispatches the SAME fused instance-cached entry as
        ``_predict_converted``.  The caller checks :meth:`_coalescible`
        first; serving an ineligible model here would silently change
        semantics, so it raises instead."""
        s = self._packed(0, -1)
        if s is None or s["_linear"]:
            raise ValueError(
                "predict_coalesced: model is not coalescible (empty or "
                "linear-leaf ensemble) — route through predict()")
        k = self.num_tree_per_iteration
        t0c0 = self._serve_t0()
        nb = x.shape[0]
        _san.record_dispatch()
        if convert:
            run = self._get_convert_entry()
            out = run(x, s["split_feature"], s["threshold"],
                      s["default_left"], s["missing_type"], s["left_child"],
                      s["right_child"], s["num_leaves"], s["leaf_value"],
                      s.get("is_cat"), s.get("cat_base"), s.get("cat_nwords"),
                      s.get("cat_words"), active, k=k)
            res = np.asarray(_san.sync_pull(out)[:n])
        else:
            cat_kw = {}
            if "is_cat" in s:
                cat_kw = dict(cat_words=s["cat_words"])
            fn = self._coalesced_raw_fn(k)
            kkw = {} if k == 1 else dict(k=k)
            out = fn(x, s["split_feature"], s["threshold"],
                     s["default_left"], s["missing_type"], s["left_child"],
                     s["right_child"], s["num_leaves"], s["leaf_value"],
                     is_cat=s.get("is_cat"), cat_base=s.get("cat_base"),
                     cat_nwords=s.get("cat_nwords"), active=active,
                     **kkw, **cat_kw)
            n_per_class = max(s["T"] // k, 1)
            scale = (1.0 / n_per_class) if self.average_output else 1.0
            res = np.asarray(_san.sync_pull(out)[:n], dtype=np.float64) * scale
        self._serve_note("coalesced", n, t0c0, bucket=nb,
                         trace_ctx=trace_ctx)
        return res

    def predict(self, X, raw_score=False, start_iteration=0, num_iteration=-1,
                pred_leaf=False, pred_contrib=False, mesh=None) -> np.ndarray:
        """``mesh=`` routes the raw traversal through the row-sharded
        giant-batch entry (:meth:`predict_raw_sharded`) — bitwise the
        single-device result.  Early-stopping, pred_leaf and pred_contrib
        have data-dependent/host-side structure and keep the single-device
        path even when a mesh is passed."""
        X = np.asarray(X, dtype=np.float64)
        if pred_leaf:
            return self._predict_leaf(X, start_iteration, num_iteration)
        if pred_contrib:
            return self.predict_contrib(X, start_iteration, num_iteration)
        early_stop = (
            self.cfg.pred_early_stop
            and not self.average_output  # RF averages trees; chunked sums break it
            and self.objective is not None
            and getattr(self.objective, "name", "") in ("binary", "multiclass", "multiclassova")
        )
        if (
            not raw_score
            and not early_stop
            and mesh is None
            and self.objective is not None
            # RF scales raw margins by 1/T on the host in f64 before
            # converting — keep that exact path rather than re-deriving it
            and not self.average_output
            and os.environ.get("LGBMTPU_FUSED_CONVERT", "1") != "0"
        ):
            res = self._predict_converted(X, start_iteration, num_iteration)
            if res is not None:
                return res
        if early_stop:
            raw = self._predict_raw_early_stop(X, start_iteration, num_iteration)
        elif mesh is not None:
            raw = self.predict_raw_sharded(X, mesh, start_iteration,
                                           num_iteration)
        else:
            raw = self.predict_raw(X, start_iteration, num_iteration)
        if raw_score or self.objective is None:
            return raw
        # output conversion rides the same row-bucket ladder: convert_output
        # is jitted per shape, so padding keeps it at one compile per bucket
        # (conversions are rowwise — sigmoid/exp/softmax — so padded rows
        # cannot leak into real ones)
        n = raw.shape[0]
        nb = _predict_bucket(n)
        if nb != n:
            pad = np.zeros((nb,) + raw.shape[1:], raw.dtype)
            pad[:n] = raw
            _san.record_dispatch()
            return _san.sync_pull(self.objective.convert_output(
                jnp.asarray(pad)))[:n]
        _san.record_dispatch()
        return _san.sync_pull(self.objective.convert_output(jnp.asarray(raw)))

    def _predict_leaf(self, X: np.ndarray, start_iteration: int = 0,
                      num_iteration: int = -1) -> np.ndarray:
        """``pred_leaf``: leaf index per (row, tree) — (N, T) i32.

        Round 9 routes this through the stacked device traversal
        (ops/predict.py predict_leaf_values) instead of the per-tree host
        walk: one dispatch over the cached packed ensemble, f32 decision
        semantics identical to predict_raw (leaf structure is shared with
        the value path — `_f32_threshold_upper` keeps left rows left)."""
        n = X.shape[0]
        s = self._packed(start_iteration, num_iteration)
        if s is None:
            return np.zeros((n, 0), dtype=np.int32)
        t0c0 = self._serve_t0()
        nb = _predict_bucket(n)
        x = self._pad_rows(X, nb)
        cat_kw = {}
        if "is_cat" in s:
            cat_kw = dict(
                is_cat=s["is_cat"], cat_base=s["cat_base"],
                cat_nwords=s["cat_nwords"], cat_words=s["cat_words"])
        _san.record_dispatch()
        out = predict_ops.predict_leaf_values(
            x, s["split_feature"], s["threshold"], s["default_left"],
            s["missing_type"], s["left_child"], s["right_child"],
            s["num_leaves"], **cat_kw,
        )
        res = np.asarray(_san.sync_pull(out)[:n], dtype=np.int32)
        self._serve_note("leaf", n, t0c0, bucket=nb)
        return res

    def _predict_raw_early_stop(self, X, start_iteration=0, num_iteration=-1):
        """Prediction early stopping (reference: include/LightGBM/
        prediction_early_stop.h + predictor.hpp): every pred_early_stop_freq
        trees, rows whose margin (|raw| for binary, top1-top2 for multiclass)
        exceeds pred_early_stop_margin stop accumulating further trees.

        Round 9: every chunk keeps ALL rows in the padded batch and masks
        early-stopped rows ON DEVICE (predict_ops.predict_raw_window with a
        traced tree offset over the window-padded packed ensemble), so each
        chunk reuses ONE compiled executable — the old path shrank the
        active set host-side (``X[active]``, jaxlint R8) and compiled
        O(chunks) times per distinct active-set size."""
        k = self.num_tree_per_iteration
        total = len(self.models) // k
        if num_iteration is not None and num_iteration >= 0:
            total = min(total, start_iteration + num_iteration)
        freq = max(int(self.cfg.pred_early_stop_freq), 1)
        margin = float(self.cfg.pred_early_stop_margin)
        X = np.asarray(X)
        n = X.shape[0]
        n_iters = total - start_iteration
        if n_iters <= 0:
            return self.predict_raw(X, start_iteration, 0)
        # a freq beyond the model is one all-trees chunk, not a dummy-tree
        # pad blowup (the old chunked path's min(freq, total - it))
        freq = min(freq, n_iters)
        window = freq * k
        s = self._packed(start_iteration, n_iters, pad_trees_to=window)
        if s is None:
            return self.predict_raw(X, start_iteration, 0)
        if s["_linear"]:
            # linear leaves walk on host — chunk over full rows (no device
            # executable to protect; masked accumulation keeps semantics)
            raw = None
            active = np.ones(n, dtype=bool)
            it = start_iteration
            while it < total:
                chunk = min(freq, total - it)
                part = self.predict_raw(X, it, chunk)
                raw = part if raw is None else raw + np.where(
                    (active if part.ndim == 1 else active[:, None]), part, 0.0)
                it += chunk
                active &= self._early_stop_active(raw, margin)
                if not active.any():
                    break
            return raw
        cat_kw = {}
        if "is_cat" in s:
            cat_kw = dict(cat_words=s["cat_words"])
        t0c0 = self._serve_t0()
        nb = _predict_bucket(n)
        x = self._pad_rows(X, nb)
        active = np.zeros(nb, dtype=bool)
        active[:n] = True
        shape = (n,) if k == 1 else (n, k)
        raw = np.zeros(shape, dtype=np.float64)
        for ci in range(s["T"] // window):
            _san.record_dispatch()
            out = predict_ops.predict_raw_window(
                x, jnp.int32(ci * window),
                s["split_feature"], s["threshold"], s["default_left"],
                s["missing_type"], s["left_child"], s["right_child"],
                s["num_leaves"], s["leaf_value"],
                is_cat=s.get("is_cat"), cat_base=s.get("cat_base"),
                cat_nwords=s.get("cat_nwords"),
                active=jnp.asarray(active), k=k, window=window, **cat_kw,
            )
            # the margin test is a REAL host data dependency (the loop's
            # exit condition) — one accounted blocking pull per chunk
            raw += _san.sync_pull(out)[:n].astype(np.float64)
            active[:n] &= self._early_stop_active(raw, margin)
            if not active[:n].any():
                break
        # the last chunk's sync_pull already drained the device queue, so
        # the whole-call latency is honestly attributed (every chunk ends
        # in an accounted blocking pull)
        self._serve_note("raw_early_stop", n, t0c0, bucket=nb)
        return raw

    @staticmethod
    def _early_stop_active(raw: np.ndarray, margin: float) -> np.ndarray:
        """Rows whose margin has NOT yet cleared pred_early_stop_margin."""
        if raw.ndim == 1:
            m = np.abs(raw)
        else:
            top2 = np.partition(raw, -2, axis=1)[:, -2:]
            m = top2[:, 1] - top2[:, 0]
        return m < margin

    def predict_contrib(self, X, start_iteration=0, num_iteration=-1) -> np.ndarray:
        """SHAP values via the per-tree path algorithm (reference:
        Tree::PredictContrib / TreeSHAP in tree.cpp)."""
        if any(t.is_linear for t in self.models):
            # reference: Predictor raises a fatal for contrib on linear trees
            raise ValueError("predict_contrib is not supported for linear trees")
        from .shap import tree_shap_ensemble

        k = self.num_tree_per_iteration
        # export trees fold the boost_from_average init into the first tree per
        # class, so the SHAP bias column matches predictions (the constant
        # shift lands in the expected value, not in feature attributions)
        trees = self._trees_for_export(start_iteration, num_iteration)
        return tree_shap_ensemble(trees, np.asarray(X, np.float64), k)

    def to_if_else(self) -> str:
        """Standalone C++ predictor source (reference: task=convert_model,
        GBDT::SaveModelToIfElse + Tree::ToIfElse in src/io/tree.cpp).

        Precision contract: the emitted code evaluates in float64 and
        bit-matches the host f64 tree walk (Tree.predict summed over
        exported trees).  Booster.predict runs the f32 device path, so the
        two agree only to ~1e-6 relative — same as the reference, whose
        ToIfElse output is double while GPU predict paths are float.
        """
        from .tree import tree_to_if_else

        trees = self._trees_for_export(0, -1)
        k = self.num_tree_per_iteration
        parts = [
            "// Generated by lightgbm_tpu task=convert_model",
            "#include <cmath>",
            "",
        ]
        for i, t in enumerate(trees):
            parts.append(tree_to_if_else(t, i))
            parts.append("")
        n_per_class = max(len(trees) // k, 1) if trees else 1
        scale = (1.0 / n_per_class) if self.average_output else 1.0
        calls = " + ".join(f"PredictTree{i}(x)" for i in range(len(trees))) or "0.0"
        if k == 1:
            parts.append("extern \"C\" double PredictRaw(const double* x) {")
            parts.append(f"  return ({calls}) * {scale:.17g};")
            parts.append("}")
            obj = self._objective_string()
            if obj.startswith("binary"):
                parts.append("extern \"C\" double Predict(const double* x) {")
                parts.append("  return 1.0 / (1.0 + std::exp(-PredictRaw(x)));")
                parts.append("}")
            else:
                parts.append("extern \"C\" double Predict(const double* x) {")
                parts.append("  return PredictRaw(x);")
                parts.append("}")
        else:
            parts.append(f"static const int kNumClass = {k};")
            parts.append("extern \"C\" void PredictRaw(const double* x, double* out) {")
            for c in range(k):
                terms = " + ".join(
                    f"PredictTree{i}(x)" for i in range(c, len(trees), k)
                ) or "0.0"
                parts.append(f"  out[{c}] = ({terms}) * {scale:.17g};")
            parts.append("}")
            parts.append("extern \"C\" void Predict(const double* x, double* out) {")
            parts.append("  PredictRaw(x, out);")
            parts.append("  double m = out[0]; for (int c = 1; c < kNumClass; ++c) if (out[c] > m) m = out[c];")
            parts.append("  double s = 0.0; for (int c = 0; c < kNumClass; ++c) { out[c] = std::exp(out[c] - m); s += out[c]; }")
            parts.append("  for (int c = 0; c < kNumClass; ++c) out[c] /= s;")
            parts.append("}")
        return "\n".join(parts) + "\n"

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        """reference: GBDT::FeatureImportance."""
        f = len(self.feature_names) if self.feature_names else (
            self.train_set.num_feature() if self.train_set else 0
        )
        imp = np.zeros(f, dtype=np.float64)
        for t in self.models:
            for i in range(t.num_internal):
                if importance_type == "split":
                    imp[t.split_feature[i]] += 1.0
                else:
                    imp[t.split_feature[i]] += max(float(t.split_gain[i]), 0.0)
        return imp

    # ------------------------------------------------------------------
    # model text format (reference: gbdt_model_text.cpp)
    # ------------------------------------------------------------------
    def _objective_string(self) -> str:
        o = self.cfg.objective
        if o == "binary":
            return f"binary sigmoid:{self.cfg.sigmoid:g}"
        if o in ("multiclass", "multiclassova"):
            return f"{o} num_class:{self.cfg.num_class}"
        if o == "lambdarank":
            return "lambdarank"
        if o == "regression" and self.cfg.reg_sqrt:
            # reference: RegressionL2loss::ToString emits "regression sqrt"
            return "regression sqrt"
        return o

    def _trees_for_export(self, start: int, num_iteration: int,
                          fold: bool = True) -> List[Tree]:
        """Trees with the init score folded in so the saved model is
        self-contained (reference: Tree::AddBias semantics): for gbdt/dart the
        first tree per class gets +init; for RF (averaged output) EVERY tree
        gets +init so avg(trees) = init + avg(deltas).  ``fold=False``
        returns the raw iteration window unchanged — the raw-delta
        snapshot form, which carries init separately."""
        import copy as _copy

        k = self.num_tree_per_iteration
        lo = start * k
        hi = len(self.models) if num_iteration < 0 else min((start + num_iteration) * k, len(self.models))
        trees = list(self.models[lo:hi])
        if not fold or lo != 0 or not any(s != 0.0 for s in self.init_scores):
            return trees
        if self.average_output:
            fold_idx = range(len(trees))
        else:
            fold_idx = range(min(k, len(trees)))
        for i in fold_idx:
            c = i % k
            t = _copy.deepcopy(trees[i])
            t.leaf_value = t.leaf_value + self.init_scores[c]
            t.internal_value = t.internal_value + self.init_scores[c]
            if t.is_linear and t.leaf_const is not None:
                # linear prediction reads leaf_const, not leaf_value
                t.leaf_const = t.leaf_const + self.init_scores[c]
            trees[i] = t
        return trees

    def save_model_to_string(self, num_iteration: int = -1, start_iteration: int = 0,
                             importance_type: str = None,
                             raw_deltas: bool = False) -> str:
        # never serialize (or snapshot) a model poisoned by non-finite
        # training values — the deferred guard is settled here at the latest
        self._guard_check()
        if importance_type is None:
            # reference: config saved_feature_importance_type selects the
            # importance written into the model file (0=split, 1=gain)
            importance_type = (
                "gain" if int(self.cfg.saved_feature_importance_type) == 1
                else "split"
            )
        k = self.num_tree_per_iteration
        # raw_deltas: the snapshot form (docs/ROBUSTNESS.md "Elastic fleet
        # recovery") — trees stay PURE deltas and the boost_from_average
        # init score is carried as an explicit `init_scores=` header line
        # instead of being folded into tree 0's float64 leaf values.
        # Folding rounds (fl64(v0+init)), so a resume replaying folded
        # trees reconstructs fl32(v0+init) where the live run held
        # fl32(init)+fl32(v0) — a last-ulp score skew that cascades into
        # every post-resume tree.  Raw-delta snapshots make crash-resume
        # BITWISE-identical to uninterrupted training.
        trees = self._trees_for_export(start_iteration, num_iteration,
                                       fold=not raw_deltas)
        feature_names = self.feature_names or [f"Column_{i}" for i in range(self.train_set.num_feature())]
        if self.binner is not None:
            infos = []
            for m in self.binner.mappers:
                if m.is_trivial:
                    infos.append("none")
                elif m.is_categorical:
                    infos.append(":".join(str(int(c)) for c in m.categories))
                else:
                    infos.append(f"[{m.min_value:g}:{m.max_value:g}]")
        else:
            infos = ["none"] * len(feature_names)

        blocks = [t.to_string(i, precise=raw_deltas) for i, t in enumerate(trees)]
        tree_sizes = [len(b) + 1 for b in blocks]
        lines = [
            "tree",
            f"version={_MODEL_VERSION}",
            f"num_class={self.cfg.num_class}",
            f"num_tree_per_iteration={k}",
            "label_index=0",
            f"max_feature_idx={len(feature_names) - 1}",
            f"objective={self._objective_string()}",
            *(["average_output"] if self.average_output else []),
            # exact decimal round-trip (repr) — float() recovers the same
            # f64 bits, so a resumed run rebuilds the identical score base
            *([f"init_scores=" + " ".join(repr(float(s))
                                          for s in self.init_scores)]
              if raw_deltas else []),
            "feature_names=" + " ".join(feature_names),
            "feature_infos=" + " ".join(infos),
            "tree_sizes=" + " ".join(str(s) for s in tree_sizes),
            "",
        ]
        out = "\n".join(lines) + "\n" + "\n".join(blocks)
        out += "\nend of trees\n\n"
        imp = self.feature_importance(importance_type)
        order = np.argsort(-imp, kind="stable")
        out += "feature_importances:\n"
        for i in order:
            if imp[i] > 0:
                out += f"{feature_names[i]}={imp[i]:g}\n"
        out += "\nparameters:\n"
        cfg = self.cfg.to_dict()
        for key in ("objective", "boosting", "num_iterations", "learning_rate", "num_leaves",
                    "max_depth", "min_data_in_leaf", "lambda_l1", "lambda_l2", "max_bin",
                    "num_class", "seed", "tree_learner", "device_type"):
            out += f"[{key}: {cfg.get(key)}]\n"
        out += "end of parameters\n\npandas_categorical:null\n"
        return out

    @classmethod
    def load_model_from_string(cls, model_str: str) -> "GBDT":
        header, _, rest = model_str.partition("\nTree=")
        kv = {}
        for line in header.splitlines():
            if "=" in line:
                key, v = line.split("=", 1)
                kv[key.strip()] = v.strip()
        obj_str = kv.get("objective", "regression").split()
        params: Dict[str, object] = {"objective": obj_str[0]}
        for tok in obj_str[1:]:
            if ":" in tok:
                pk, pv = tok.split(":", 1)
                params[pk] = pv
            elif tok == "sqrt":  # reference: "regression sqrt"
                params["reg_sqrt"] = True
        if int(kv.get("num_class", 1)) > 1:
            params["num_class"] = int(kv["num_class"])
        cfg = Config.from_dict(params)
        booster = cls(cfg)
        booster.feature_names = kv.get("feature_names", "").split()
        booster.num_tree_per_iteration = int(kv.get("num_tree_per_iteration", 1))
        booster.average_output = any(
            line.strip() == "average_output" for line in header.splitlines()
        )
        if "init_scores" in kv:
            # raw-delta snapshot form: trees are pure deltas, the init
            # score rides this header line (save_model_to_string raw_deltas)
            booster.init_scores = [float(v) for v in kv["init_scores"].split()]
            if len(booster.init_scores) != booster.num_tree_per_iteration:
                # a count mismatch means a torn header or a class-count
                # mix-up; silently zeroing would load a model whose
                # predictions are missing the boost_from_average base
                raise ValueError(
                    f"snapshot init_scores header has "
                    f"{len(booster.init_scores)} entries but "
                    f"num_tree_per_iteration is "
                    f"{booster.num_tree_per_iteration} — torn or "
                    "mismatched raw-delta snapshot (docs/ROBUSTNESS.md)")
        else:
            booster.init_scores = [0.0] * booster.num_tree_per_iteration  # folded into trees
        trees_part = rest.split("\nend of trees")[0]
        blocks = ("Tree=" + trees_part).split("\nTree=")
        for b in blocks:
            if b.strip():
                booster.models.append(Tree.from_string("Tree=" + b if not b.startswith("Tree=") else b))
        booster.iter_ = len(booster.models) // max(booster.num_tree_per_iteration, 1)
        return booster


class DART(GBDT):
    """reference: src/boosting/dart.hpp — dropout boosting."""

    def train_one_iter(self, grad=None, hess=None) -> bool:
        cfg = self.cfg
        k = self.num_tree_per_iteration
        n_iters_done = self.iter_
        rng = np.random.RandomState(cfg.drop_seed + n_iters_done)
        drop_idx: List[int] = []
        if n_iters_done > 0 and rng.rand() >= cfg.skip_drop:
            if cfg.uniform_drop:
                mask = rng.rand(n_iters_done) < cfg.drop_rate
                drop_idx = list(np.nonzero(mask)[0])
            else:
                want = max(int(round(n_iters_done * cfg.drop_rate)), 1)
                drop_idx = list(rng.choice(n_iters_done, size=min(want, n_iters_done), replace=False))
            drop_idx = drop_idx[: cfg.max_drop] if cfg.max_drop > 0 else drop_idx
        # remove dropped trees' contribution from scores
        self._dart_removed = []
        for it in drop_idx:
            for c in range(k):
                tree = self.models[it * k + c]
                leaf_id = self.train_set.predict_leaf_binned_tree(tree)
                vals = jnp.asarray(tree.leaf_value, jnp.float32)[leaf_id]
                if k == 1:
                    self._score = self._score - vals
                else:
                    self._score = self._score.at[:, c].add(-vals)
        finished = super().train_one_iter(grad, hess)
        # normalization (reference: DART::Normalize)
        n_drop = len(drop_idx)
        if n_drop > 0:
            if cfg.xgboost_dart_mode:
                new_scale = cfg.learning_rate / (n_drop + cfg.learning_rate)
                old_scale = n_drop / (n_drop + cfg.learning_rate)
            else:
                new_scale = 1.0 / (n_drop + 1.0)
                old_scale = n_drop / (n_drop + 1.0)
            for c in range(k):
                new_tree = self.models[-k + c]
                new_tree.apply_shrinkage(new_scale)
            for it in drop_idx:
                for c in range(k):
                    self.models[it * k + c].apply_shrinkage(old_scale)
            # rebuild scores: add back dropped trees (rescaled) and fix new tree scale
            for it in drop_idx:
                for c in range(k):
                    tree = self.models[it * k + c]
                    leaf_id = self.train_set.predict_leaf_binned_tree(tree)
                    vals = jnp.asarray(tree.leaf_value, jnp.float32)[leaf_id]
                    if k == 1:
                        self._score = self._score + vals
                    else:
                        self._score = self._score.at[:, c].add(vals)
            for c in range(k):
                tree = self.models[-k + c]
                leaf_id = self.train_set.predict_leaf_binned_tree(tree)
                # score currently holds the un-rescaled new tree: subtract the difference
                vals = jnp.asarray(tree.leaf_value, jnp.float32)[leaf_id]
                corr = vals * (1.0 / new_scale - 1.0)
                if k == 1:
                    self._score = self._score - corr
                else:
                    self._score = self._score.at[:, c].add(-corr)
        return finished


class RF(GBDT):
    """reference: src/boosting/rf.hpp — bagging-only forest, averaged output."""

    average_output = True

    def __init__(self, cfg: Config, train_set=None, objective=None):
        if cfg.bagging_freq <= 0 or cfg.bagging_fraction >= 1.0:
            raise ValueError("Random forest needs bagging (bagging_freq > 0 and bagging_fraction < 1)")
        super().__init__(cfg, train_set, objective)

    def train_one_iter(self, grad=None, hess=None) -> bool:
        # RF computes gradients at the (fixed) init score every iteration
        if grad is None and self.objective is not None:
            base = jnp.zeros_like(self._score) + jnp.asarray(
                np.asarray(self.init_scores, dtype=np.float32)
                if self.num_tree_per_iteration > 1 else np.float32(self.init_scores[0])
            )
            g, h = self.objective.get_gradients(base, self._label, self._weight)
            grad, hess = np.asarray(g), np.asarray(h)
        return super().train_one_iter(grad, hess)

    def _eval_margin(self, score):
        # _score holds init + sum(deltas); metrics need init + mean(deltas)
        init = np.asarray(self.init_scores, dtype=np.float32)
        init = init[0] if self.num_tree_per_iteration == 1 else init[None, :]
        return init + (score - init) / max(self.iter_, 1)


def create_boosting(cfg: Config, train_set=None) -> GBDT:
    """reference: Boosting::CreateBoosting in src/boosting/boosting.cpp."""
    name = cfg.boosting
    if name in ("gbdt", "gbrt", "goss"):
        if name == "goss":
            cfg.data_sample_strategy = "goss"
        return GBDT(cfg, train_set)
    if name == "dart":
        return DART(cfg, train_set)
    if name in ("rf", "random_forest"):
        return RF(cfg, train_set)
    raise ValueError(f"Unknown boosting type: {name}")

def _parse_interaction_constraints(spec, feature_names):
    """Parse interaction_constraints: "[0,1,2],[2,3]" or list of lists of
    feature indices/names (reference: Config interaction_constraints string)."""
    if not spec:
        return []
    if isinstance(spec, str):
        import re

        groups = re.findall(r"\[([^\]]*)\]", spec)
        sets = []
        for g in groups:
            items = [t.strip() for t in g.split(",") if t.strip()]
            sets.append(items)
    else:
        sets = [list(g) for g in spec]
    out = []
    name_to_idx = {n: i for i, n in enumerate(feature_names or [])}
    for g in sets:
        idxs = []
        for it in g:
            if isinstance(it, str) and not it.lstrip("-").isdigit():
                if it in name_to_idx:
                    idxs.append(name_to_idx[it])
            else:
                idxs.append(int(it))
        out.append(idxs)
    return out
