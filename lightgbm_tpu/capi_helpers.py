"""Python side of the C API shim (src/capi/lightgbm_tpu_c_api.cpp).

The C layer passes raw pointers as integers; numpy wraps them zero-copy via
ctypes, mirroring the reference's c_api.cpp which operates directly on the
caller's buffers.  Kept deliberately thin: every function takes/returns
plain scalars, strings or Booster objects so the C side needs no numpy ABI.
"""

from __future__ import annotations

import ctypes

import numpy as np

from .basic import Booster, Dataset, LightGBMError

_PREDICT_NORMAL = 0
_PREDICT_RAW_SCORE = 1
_PREDICT_LEAF_INDEX = 2
_PREDICT_CONTRIB = 3

# reference: C_API_DTYPE_* in include/LightGBM/c_api.h
_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}
_CTYPES = {0: ctypes.c_float, 1: ctypes.c_double, 2: ctypes.c_int32, 3: ctypes.c_int64}


def _parse_params(parameters: str) -> dict:
    """reference: Config::Str2Map — 'k1=v1 k2=v2' (space/newline separated)."""
    out = {}
    for tok in parameters.replace("\n", " ").split():
        if "=" not in tok:
            continue
        k, v = tok.split("=", 1)
        for cast in (int, float):
            try:
                v = cast(v)
                break
            except ValueError:
                continue
        if isinstance(v, str):
            # bool-likes must not stay truthy strings ('header=false' would
            # otherwise drop the first data row); mirror Config._coerce
            low = v.lower()
            if low in ("true", "+", "yes"):
                v = True
            elif low in ("false", "-", "no"):
                v = False
        out[k] = v
    return out


def booster_from_file(filename: str) -> Booster:
    return Booster(model_file=filename)


def booster_from_string(model_str: str) -> Booster:
    return Booster(model_str=model_str)


def num_classes(bst: Booster) -> int:
    return int(getattr(bst._gbdt, "num_tree_per_iteration", 1))


def save_model(bst: Booster, filename: str, start_iteration: int,
               num_iteration: int) -> bool:
    bst.save_model(filename, num_iteration=num_iteration,
                   start_iteration=start_iteration)
    return True


def _wrap(addr: int, shape, dtype=np.float64) -> np.ndarray:
    size = int(np.prod(shape))
    ctype = ctypes.c_double if dtype == np.float64 else ctypes.c_float
    buf = (ctype * size).from_address(addr)
    return np.frombuffer(buf, dtype=dtype).reshape(shape)


# -- dataset surface (reference: LGBM_Dataset*) --------------------------

def _wrap_typed(addr: int, shape, dtype_code: int) -> np.ndarray:
    size = int(np.prod(shape))
    buf = (_CTYPES[dtype_code] * size).from_address(addr)
    return np.frombuffer(buf, dtype=_DTYPES[dtype_code]).reshape(shape)


def dataset_from_mat(data_addr: int, dtype_code: int, nrow: int, ncol: int,
                     is_row_major: int, parameters: str, reference) -> Dataset:
    if is_row_major:
        x = _wrap_typed(data_addr, (nrow, ncol), dtype_code)
    else:
        x = _wrap_typed(data_addr, (ncol, nrow), dtype_code).T
    # copy: the Dataset outlives the caller's buffer (reference copies into
    # its own bins during construction as well)
    ds = Dataset(np.array(x, np.float64), params=_parse_params(parameters),
                 reference=reference if isinstance(reference, Dataset) else None,
                 free_raw_data=False)
    return ds


def dataset_from_file(filename: str, parameters: str, reference) -> Dataset:
    from .io.parser import load_data_file

    params = _parse_params(parameters)
    loaded = load_data_file(
        filename,
        header=bool(params.get("header", False)),
        label_column=str(params.get("label_column", "")),
        weight_column=str(params.get("weight_column", "")),
        group_column=str(params.get("group_column", "")),
        ignore_column=str(params.get("ignore_column", "")),
    )
    ds = Dataset(loaded["data"], label=loaded.get("label"),
                 weight=loaded.get("weight"), group=loaded.get("group"),
                 params=params,
                 reference=reference if isinstance(reference, Dataset) else None,
                 free_raw_data=False)
    return ds


def dataset_set_field(ds, field_name: str, data_addr: int,
                      num_element: int, dtype_code: int) -> bool:
    if num_element == 0 or data_addr == 0:
        ds.set_field(field_name, None)  # reference: zero-length clears
        return True
    arr = np.array(_wrap_typed(data_addr, (num_element,), dtype_code))
    ds.set_field(field_name, arr)  # Dataset and StreamingDataset both accept
    return True


def dataset_get_num_data(ds) -> int:
    return int(_as_dataset(ds).num_data())


def dataset_get_num_feature(ds) -> int:
    return int(_as_dataset(ds).num_feature())


def dataset_get_feature_num_bin(ds, feature_idx: int) -> int:
    """reference: LGBM_DatasetGetFeatureNumBin -> Dataset::FeatureNumBin."""
    d = _as_dataset(ds)
    d.construct()
    nbpf = d.binner.num_bins_per_feature
    if not (0 <= feature_idx < len(nbpf)):
        raise IndexError(f"feature index {feature_idx} out of range")
    return int(nbpf[feature_idx])


class StreamingDataset:
    """Push-rows accumulator (reference: LGBM_DatasetCreateByReference +
    LGBM_DatasetPushRows streaming construction).  Rows stream into a
    preallocated buffer; the real Dataset materializes bin-aligned to the
    reference once all rows have arrived."""

    def __init__(self, reference: Dataset, num_total_row: int):
        reference.construct()
        self.reference = reference
        self.num_total = int(num_total_row)
        self.ncol = reference.num_feature()
        self.buf = np.full((self.num_total, self.ncol), np.nan, np.float64)
        self.fields = {}
        self.pushed = 0
        self._ds = None

    def push(self, rows: np.ndarray, start_row: int) -> None:
        n = rows.shape[0]
        self.buf[start_row: start_row + n] = rows
        self.pushed += n

    def set_field(self, name, arr):
        self.fields[name] = arr

    def dataset(self) -> Dataset:
        if self._ds is None:
            if self.pushed < self.num_total and not getattr(self, "_finished", False):
                raise ValueError(
                    f"only {self.pushed}/{self.num_total} rows pushed")
            names = list(getattr(self.reference, "feature_names", []) or [])
            self._ds = Dataset(self.buf, reference=self.reference,
                              feature_name=names or "auto",
                              free_raw_data=False)
            for k, v in self.fields.items():
                self._ds.set_field(k, v)
        return self._ds


def _as_dataset(ds) -> Dataset:
    return ds.dataset() if isinstance(ds, StreamingDataset) else ds


def dataset_create_by_reference(reference: Dataset, num_total_row: int) -> StreamingDataset:
    return StreamingDataset(_as_dataset(reference), num_total_row)


def dataset_push_rows(ds: StreamingDataset, data_addr: int, dtype_code: int,
                      nrow: int, ncol: int, start_row: int) -> bool:
    rows = np.array(_wrap_typed(data_addr, (nrow, ncol), dtype_code), np.float64)
    ds.push(rows, start_row)
    return True


# -- booster training surface (reference: LGBM_Booster*) ------------------

def booster_create(train_set, parameters: str) -> Booster:
    params = _parse_params(parameters)
    if _NETWORK_PARAMS:  # LGBM_NetworkInit state is global, like the reference
        params = dict(_NETWORK_PARAMS, **params)
    return Booster(params=params, train_set=_as_dataset(train_set))


def booster_add_valid(bst: Booster, valid_set) -> bool:
    valid_set = _as_dataset(valid_set)
    name = f"valid_{len(getattr(bst._gbdt, 'valid_sets', []))}"
    bst.add_valid(valid_set, name)
    return True


def booster_update(bst: Booster) -> int:
    # the reference's LGBM_BoosterUpdateOneIter reports is_finished per call;
    # flip the fused path from its deferred (every-32) check to the
    # one-iteration-late async probe
    bst._gbdt._report_finish_every_iter = True
    return 1 if bst.update() else 0


def booster_update_custom(bst: Booster, grad_addr: int, hess_addr: int) -> int:
    n = bst._train_set.num_data() * num_classes(bst)
    grad = np.array(_wrap_typed(grad_addr, (n,), 0), np.float64)
    hess = np.array(_wrap_typed(hess_addr, (n,), 0), np.float64)
    return 1 if bst._gbdt.train_one_iter(grad, hess) else 0


def booster_rollback(bst: Booster) -> bool:
    bst.rollback_one_iter()
    return True


def booster_current_iteration(bst: Booster) -> int:
    return int(bst.current_iteration())


def booster_num_total_model(bst: Booster) -> int:
    return int(bst.num_trees())


def booster_num_feature(bst: Booster) -> int:
    return int(bst.num_feature())


def booster_reset_parameter(bst: Booster, parameters: str) -> bool:
    bst.reset_parameter(_parse_params(parameters))
    return True


def booster_reset_training_data(bst: Booster, train_set) -> bool:
    """reference: LGBM_BoosterResetTrainingData -> GBDT::ResetTrainingData
    (existing trees kept; subsequent updates train on the new data)."""
    ds = _as_dataset(train_set)
    bst._train_set = ds
    bst._gbdt.reset_training_data(ds)
    return True


def booster_eval_counts(bst: Booster) -> int:
    res = bst.eval_train()
    return len(res)


def booster_get_eval_into(bst: Booster, data_idx: int, out_addr: int) -> int:
    """data_idx 0 = train, i>0 = i-th valid set (reference:
    LGBM_BoosterGetEval)."""
    res = bst.eval_train() if data_idx == 0 else bst.eval_valid()
    if data_idx > 0:
        # filter to the requested valid set (eval_valid returns all); the
        # reference indexes valid sets by REGISTRATION order, and sorting
        # would misorder >=10 auto-named sets ('valid_10' < 'valid_2')
        names = list(getattr(bst._gbdt, "valid_names", []))
        if data_idx - 1 >= len(names):
            return 0  # out-of-range index must not spill all sets' metrics
        want = names[data_idx - 1]
        res = [r for r in res if r[0] == want]
    vals = np.asarray([r[2] for r in res], np.float64)
    dest = _wrap(out_addr, (len(vals),))
    dest[:] = vals
    return len(vals)


def booster_save_string(bst: Booster, start_iteration: int,
                        num_iteration: int) -> str:
    return bst.model_to_string(num_iteration=num_iteration,
                               start_iteration=start_iteration)


def booster_dump_json(bst: Booster, start_iteration: int,
                      num_iteration: int) -> str:
    import json

    return json.dumps(bst.dump_model(num_iteration=num_iteration,
                                     start_iteration=start_iteration),
                      default=float)


def booster_feature_importance_into(bst: Booster, importance_type: int,
                                    out_addr: int) -> int:
    imp = bst.feature_importance("gain" if importance_type == 1 else "split")
    dest = _wrap(out_addr, (len(imp),))
    dest[:] = np.asarray(imp, np.float64)
    return len(imp)


def predict_into(bst: Booster, data_addr: int, data_type: int, nrow: int,
                 ncol: int, is_row_major: int, predict_type: int,
                 start_iteration: int, num_iteration: int, parameter: str,
                 out_addr: int) -> int:
    if is_row_major:
        x = _wrap_typed(data_addr, (nrow, ncol), data_type)
    else:
        x = _wrap_typed(data_addr, (ncol, nrow), data_type).T
    return _predict_any_into(bst, x, predict_type, out_addr,
                             **_predict_kw(start_iteration, num_iteration,
                                           parameter))


# ---- CSR surface (reference: LGBM_DatasetCreateFromCSR /
#      LGBM_BoosterPredictForCSR in src/c_api.cpp) ----

def _wrap_csr(indptr_addr: int, indptr_type: int, indices_addr: int,
              data_addr: int, data_type: int, nindptr: int, nelem: int,
              num_col: int):
    import scipy.sparse as sp

    indptr = np.array(_wrap_typed(indptr_addr, (nindptr,), indptr_type))
    indices = np.array(_wrap_typed(indices_addr, (nelem,), 2))  # int32
    data = np.array(_wrap_typed(data_addr, (nelem,), data_type))
    return sp.csr_matrix((data, indices, indptr),
                         shape=(nindptr - 1, num_col))


def dataset_from_csr(indptr_addr: int, indptr_type: int, indices_addr: int,
                     data_addr: int, data_type: int, nindptr: int,
                     nelem: int, num_col: int, parameters: str,
                     reference) -> Dataset:
    x = _wrap_csr(indptr_addr, indptr_type, indices_addr, data_addr,
                  data_type, nindptr, nelem, num_col)
    return Dataset(x, params=_parse_params(parameters),
                   reference=reference if isinstance(reference, Dataset) else None,
                   free_raw_data=False)


def predict_csr_into(bst: Booster, indptr_addr: int, indptr_type: int,
                     indices_addr: int, data_addr: int, data_type: int,
                     nindptr: int, nelem: int, num_col: int,
                     predict_type: int, start_iteration: int,
                     num_iteration: int, parameter: str,
                     out_addr: int) -> int:
    x = _wrap_csr(indptr_addr, indptr_type, indices_addr, data_addr,
                  data_type, nindptr, nelem, num_col)
    return _predict_any_into(bst, x, predict_type, out_addr,
                             **_predict_kw(start_iteration, num_iteration,
                                           parameter))


def _predict_kw(start_iteration: int = 0, num_iteration: int = -1,
                parameter: str = "") -> dict:
    """Predict kwargs from the reference C predict-entry triple
    (start_iteration, num_iteration, parameter).  The explicit C arguments
    win over any duplicates inside the parameter string (reference:
    LGBM_BoosterPredictForMat passes them straight into the Config).
    Predict-MODE keys are dropped too: the C predict_type argument is
    authoritative and _predict_any_into passes the matching kwarg
    explicitly — forwarding a duplicate from the string would raise
    TypeError where the reference Config just tolerates it."""
    kw = _parse_params(parameter or "")
    for mode_key in ("raw_score", "predict_raw_score", "pred_leaf",
                     "predict_leaf_index", "pred_contrib", "predict_contrib",
                     "leaf_index", "contrib", "is_predict_raw_score",
                     "is_predict_leaf_index", "is_predict_contrib"):
        kw.pop(mode_key, None)
    kw["start_iteration"] = int(start_iteration)
    kw["num_iteration"] = int(num_iteration)
    return kw


def _predict_any_into(bst: Booster, x, predict_type: int, out_addr: int,
                      **kw) -> int:
    if predict_type == _PREDICT_LEAF_INDEX:
        out = bst.predict(x, pred_leaf=True, **kw).astype(np.float64)
    elif predict_type == _PREDICT_CONTRIB:
        out = bst.predict(x, pred_contrib=True, **kw)
    elif predict_type == _PREDICT_RAW_SCORE:
        out = bst.predict(x, raw_score=True, **kw)
    else:
        out = bst.predict(x, **kw)
    out = np.ascontiguousarray(out, np.float64).ravel()
    dest = _wrap(out_addr, (out.size,))
    dest[:] = out
    return int(out.size)


# ---- single-row fast predict (reference: SingleRowPredictor +
#      LGBM_BoosterPredictForMatSingleRowFast / FastConfigHandle) ----

class _FastConfig:
    """Opaque FastConfig handle: booster + frozen predict settings
    (reference: FastConfig in src/c_api.cpp — caches everything so the
    per-call path only reads one row and writes one result)."""

    def __init__(self, bst: Booster, predict_type: int, data_type: int,
                 ncol: int, parameters: str = ""):
        self.bst = bst
        self.predict_type = predict_type
        self.data_type = data_type
        self.ncol = ncol
        p = _parse_params(parameters)
        self.num_iteration = int(p.pop("num_iteration", -1))
        self.start_iteration = int(p.pop("start_iteration", 0))
        self.kwargs = p  # e.g. predict_disable_shape_check


def predict_single_row_fast_init(bst: Booster, predict_type: int,
                                 start_iteration: int, num_iteration: int,
                                 data_type: int, ncol: int,
                                 parameters: str = "") -> _FastConfig:
    cfg = _FastConfig(bst, predict_type, data_type, ncol, parameters)
    # the explicit C arguments win over duplicates in the parameter string
    cfg.start_iteration = int(start_iteration)
    cfg.num_iteration = int(num_iteration)
    # serving warm-up (round 9): pack the ensemble into the device-resident
    # cache and compile the single-row bucket NOW, so the steady-state
    # per-call path is one warm dispatch — init pays the cold cost once
    # (reference: SingleRowPredictor caches its Predictor the same way)
    if predict_type in (_PREDICT_NORMAL, _PREDICT_RAW_SCORE,
                        _PREDICT_LEAF_INDEX):
        try:
            # one dummy predict packs the exact (start, num) ensemble the
            # per-call path will serve AND compiles its 1-row bucket
            bst.predict(np.zeros((1, ncol)),
                        start_iteration=cfg.start_iteration,
                        num_iteration=cfg.num_iteration,
                        raw_score=cfg.predict_type == _PREDICT_RAW_SCORE,
                        pred_leaf=cfg.predict_type == _PREDICT_LEAF_INDEX,
                        **cfg.kwargs)
        except Exception:  # noqa: BLE001 — warm-up must never fail init
            pass
    return cfg


def predict_single_row_fast(cfg: _FastConfig, data_addr: int,
                            out_addr: int) -> int:
    x = np.array(_wrap_typed(data_addr, (1, cfg.ncol), cfg.data_type),
                 np.float64)
    return _predict_any_into(cfg.bst, x, cfg.predict_type, out_addr,
                             num_iteration=cfg.num_iteration,
                             start_iteration=cfg.start_iteration,
                             **cfg.kwargs)


def predict_single_row_into(bst: Booster, data_addr: int, ncol: int,
                            data_type: int, predict_type: int,
                            start_iteration: int, num_iteration: int,
                            parameter: str, out_addr: int) -> int:
    x = np.array(_wrap_typed(data_addr, (1, ncol), data_type), np.float64)
    return _predict_any_into(bst, x, predict_type, out_addr,
                             **_predict_kw(start_iteration, num_iteration,
                                           parameter))


# ---- CSC surface (reference: LGBM_DatasetCreateFromCSC /
#      LGBM_BoosterPredictForCSC in src/c_api.cpp) ----

def _wrap_csc(colptr_addr: int, colptr_type: int, indices_addr: int,
              data_addr: int, data_type: int, ncolptr: int, nelem: int,
              num_row: int):
    import scipy.sparse as sp

    colptr = np.array(_wrap_typed(colptr_addr, (ncolptr,), colptr_type))
    indices = np.array(_wrap_typed(indices_addr, (nelem,), 2))  # int32
    data = np.array(_wrap_typed(data_addr, (nelem,), data_type))
    return sp.csc_matrix((data, indices, colptr),
                         shape=(num_row, ncolptr - 1))


def dataset_from_csc(colptr_addr: int, colptr_type: int, indices_addr: int,
                     data_addr: int, data_type: int, ncolptr: int,
                     nelem: int, num_row: int, parameters: str,
                     reference) -> Dataset:
    x = _wrap_csc(colptr_addr, colptr_type, indices_addr, data_addr,
                  data_type, ncolptr, nelem, num_row)
    return Dataset(x, params=_parse_params(parameters),
                   reference=reference if isinstance(reference, Dataset) else None,
                   free_raw_data=False)


def predict_sparse_output(bst: Booster, indptr_addr: int, indptr_type: int,
                          indices_addr: int, data_addr: int, data_type: int,
                          nindptr: int, nelem: int, num_col_or_row: int,
                          predict_type: int, start_iteration: int,
                          num_iteration: int, parameter: str,
                          matrix_type: int) -> tuple:
    """reference: LGBM_BoosterPredictSparseOutput — SHAP contributions as a
    library-allocated sparse matrix (CSR for matrix_type 0, CSC for 1; the
    input shares the same layout).  Only C_API_PREDICT_CONTRIB is legal,
    matching the reference's check.  Returns
    (indptr_addr, indices_addr, data_addr, n_indptr, nnz) where the three
    buffers are malloc()'d here (libc) so LGBM_BoosterFreePredictSparse can
    free() them from C; indptr is written in indptr_type, data in the
    REQUESTED data_type — f32 or f64, exactly like the reference
    allocates per data_type (round 7 closed the f64-only deviation
    PARITY.md carried).  Multiclass contribs are laid out as
    (nrow, num_class*(num_feature+1)), the reference's dense flattening."""
    import ctypes.util
    import scipy.sparse as sp

    if predict_type != _PREDICT_CONTRIB:
        raise ValueError(
            "LGBM_BoosterPredictSparseOutput only supports predict_type="
            "C_API_PREDICT_CONTRIB (reference: c_api.cpp same check)")
    if matrix_type == 0:  # CSR input/output
        x = _wrap_csr(indptr_addr, indptr_type, indices_addr, data_addr,
                      data_type, nindptr, nelem, num_col_or_row)
    else:  # CSC
        x = _wrap_csc(indptr_addr, indptr_type, indices_addr, data_addr,
                      data_type, nindptr, nelem, num_col_or_row)
    contrib = bst.predict(
        x, pred_contrib=True,
        **_predict_kw(start_iteration, num_iteration, parameter))
    # sparsify in f64 (exact zero detection on the model's own outputs),
    # then narrow the kept values to the caller's requested dtype
    contrib = np.ascontiguousarray(
        np.asarray(contrib, np.float64).reshape(x.shape[0], -1))
    mat = (sp.csr_matrix(contrib) if matrix_type == 0
           else sp.csc_matrix(contrib))
    out_indptr = np.asarray(
        mat.indptr, np.int64 if indptr_type == 3 else np.int32)
    out_indices = np.asarray(mat.indices, np.int32)
    out_data = np.asarray(
        mat.data, np.float32 if data_type == 0 else np.float64)

    libc = ctypes.CDLL(None)
    libc.malloc.restype = ctypes.c_void_p
    libc.malloc.argtypes = [ctypes.c_size_t]

    def _to_c(arr):
        nb = max(arr.nbytes, 1)
        addr = libc.malloc(nb)
        if not addr:
            raise MemoryError(f"malloc({nb}) failed")
        ctypes.memmove(addr, arr.ctypes.data, arr.nbytes)
        return addr

    return (_to_c(out_indptr), _to_c(out_indices), _to_c(out_data),
            int(len(out_indptr)), int(len(out_data)))


def predict_csc_into(bst: Booster, colptr_addr: int, colptr_type: int,
                     indices_addr: int, data_addr: int, data_type: int,
                     ncolptr: int, nelem: int, num_row: int,
                     predict_type: int, start_iteration: int,
                     num_iteration: int, parameter: str,
                     out_addr: int) -> int:
    x = _wrap_csc(colptr_addr, colptr_type, indices_addr, data_addr,
                  data_type, ncolptr, nelem, num_row)
    return _predict_any_into(bst, x, predict_type, out_addr,
                             **_predict_kw(start_iteration, num_iteration,
                                           parameter))


# ---- multi-block matrices (reference: LGBM_DatasetCreateFromMats /
#      LGBM_BoosterPredictForMats) ----

def _wrap_mats(nmat: int, data_ptrs_addr: int, dtype_code: int,
               nrow_addr: int, ncol: int, is_row_major: int) -> np.ndarray:
    ptrs = np.array(_wrap_typed(data_ptrs_addr, (nmat,), 3))  # void** as i64
    nrows = np.array(_wrap_typed(nrow_addr, (nmat,), 2))
    blocks = []
    for p, nr in zip(ptrs, nrows):
        if is_row_major:
            b = _wrap_typed(int(p), (int(nr), ncol), dtype_code)
        else:
            b = _wrap_typed(int(p), (ncol, int(nr)), dtype_code).T
        blocks.append(np.array(b, np.float64))
    return np.vstack(blocks)


def dataset_from_mats(nmat: int, data_ptrs_addr: int, dtype_code: int,
                      nrow_addr: int, ncol: int, is_row_major: int,
                      parameters: str, reference) -> Dataset:
    x = _wrap_mats(nmat, data_ptrs_addr, dtype_code, nrow_addr, ncol,
                   is_row_major)
    return Dataset(x, params=_parse_params(parameters),
                   reference=reference if isinstance(reference, Dataset) else None,
                   free_raw_data=False)


def predict_mats_into(bst: Booster, nmat: int, data_ptrs_addr: int,
                      dtype_code: int, nrow_addr: int, ncol: int,
                      predict_type: int, start_iteration: int,
                      num_iteration: int, parameter: str,
                      out_addr: int) -> int:
    x = _wrap_mats(nmat, data_ptrs_addr, dtype_code, nrow_addr, ncol, 1)
    return _predict_any_into(bst, x, predict_type, out_addr,
                             **_predict_kw(start_iteration, num_iteration,
                                           parameter))


# ---- sampled-column schema construction (reference:
#      LGBM_DatasetCreateFromSampledColumn → DatasetLoader::
#      ConstructFromSampleData: bin mappers come from the per-column value
#      sample; rows stream in afterwards via PushRows) ----

def dataset_from_sampled_column(sample_ptrs_addr: int, indices_ptrs_addr: int,
                                ncol: int, num_per_col_addr: int,
                                num_sample_row: int, num_local_row: int,
                                parameters: str) -> "StreamingDataset":
    col_ptrs = np.array(_wrap_typed(sample_ptrs_addr, (ncol,), 3))
    idx_ptrs = np.array(_wrap_typed(indices_ptrs_addr, (ncol,), 3))
    counts = np.array(_wrap_typed(num_per_col_addr, (ncol,), 2))
    sample = np.zeros((num_sample_row, ncol), np.float64)
    for c in range(ncol):
        k = int(counts[c])
        if k == 0:
            continue
        vals = np.array(_wrap_typed(int(col_ptrs[c]), (k,), 1))
        rows = np.array(_wrap_typed(int(idx_ptrs[c]), (k,), 2))
        sample[rows, c] = vals
    schema = Dataset(sample, params=_parse_params(parameters),
                     free_raw_data=False)
    schema.construct()
    return StreamingDataset(schema, num_local_row)


# ---- dataset field / name / persistence surface ------------------------

# reference: LGBM_DatasetGetField returns a pointer into dataset-owned
# memory typed per field (label/weight float32, init_score float64,
# group int32 boundaries).
_FIELD_OUT_TYPES = {"label": 0, "weight": 0, "init_score": 1,
                    "group": 2, "query": 2, "position": 2}


def dataset_get_field(ds, field_name: str):
    """Returns (addr, num_element, dtype_code); the array stays alive on the
    dataset (reference hands out internal pointers the same way)."""
    ds = _as_dataset(ds)
    val = ds.get_field(field_name)
    code = _FIELD_OUT_TYPES.get(field_name)
    if code is None:
        raise ValueError(f"Unknown field: {field_name}")
    if val is None:
        return (0, 0, code)
    if field_name in ("group", "query"):
        # sizes -> cumulative boundaries, as the reference returns
        val = ds.query_boundaries
    arr = np.ascontiguousarray(val, _DTYPES[code])
    if not hasattr(ds, "_capi_field_cache"):
        ds._capi_field_cache = {}
    ds._capi_field_cache[field_name] = arr
    return (int(arr.ctypes.data), int(arr.size), code)


def dataset_set_feature_names(ds, names) -> bool:
    _as_dataset(ds).set_feature_name(list(names))
    return True


def dataset_feature_names(ds):
    return list(_as_dataset(ds).get_feature_name())


def dataset_save_binary(ds, filename: str) -> bool:
    _as_dataset(ds).save_binary(filename)
    return True


def dataset_dump_text(ds, filename: str) -> bool:
    """reference: LGBM_DatasetDumpText — human-readable dataset dump."""
    ds = _as_dataset(ds)
    ds.construct()
    with open(filename, "w") as f:
        f.write("\t".join(ds.get_feature_name()) + "\n")
        data = ds.get_data()
        if data is not None:
            arr = np.asarray(data if not hasattr(data, "toarray") else data.toarray())
            for row in arr:
                f.write("\t".join(repr(float(v)) for v in row) + "\n")
        else:  # raw freed: dump binned values (still row-per-line)
            for row in ds._host_bins("dump_text"):
                f.write("\t".join(str(int(v)) for v in row) + "\n")
    return True


def dataset_get_subset(ds, indices_addr: int, num_indices: int,
                       parameters: str) -> Dataset:
    idx = np.array(_wrap_typed(indices_addr, (num_indices,), 2))
    return _as_dataset(ds).subset(idx, params=_parse_params(parameters))


def dataset_add_features_from(target, source) -> bool:
    _as_dataset(target).add_features_from(_as_dataset(source))
    return True


# params that change the binned representation; changing them between a
# reference dataset and a dependent one is the conflict the reference's
# LGBM_DatasetUpdateParamChecking exists to catch
_DATASET_PARAMS = (
    "max_bin", "min_data_in_bin", "bin_construct_sample_cnt",
    "zero_as_missing", "use_missing", "enable_bundle", "max_bin_by_feature",
    "categorical_feature", "feature_pre_filter", "two_round", "header",
    "label_column", "weight_column", "group_column", "ignore_column",
    "precise_float_parser", "forcedbins_filename", "linear_tree",
)


def dataset_update_param_checking(old_parameters: str,
                                  new_parameters: str) -> bool:
    from .config import Config

    old = _parse_params(old_parameters)
    new = _parse_params(new_parameters)
    # compare EFFECTIVE values: a new param restating the default the old
    # config already had is not a conflict (reference builds Configs from
    # both strings and diffs them)
    cfg_old = Config.from_dict(old)
    cfg_new = Config.from_dict(dict(old, **new))

    def effective(cfg, key):
        return getattr(cfg, key, cfg.extra.get(key))

    for k in _DATASET_PARAMS:
        if effective(cfg_old, k) != effective(cfg_new, k):
            raise ValueError(
                f"Cannot change {k} after constructed Dataset handle")
    return True


def dataset_push_rows_by_csr(ds: "StreamingDataset", indptr_addr: int,
                             indptr_type: int, indices_addr: int,
                             data_addr: int, data_type: int, nindptr: int,
                             nelem: int, num_col: int, start_row: int) -> bool:
    x = _wrap_csr(indptr_addr, indptr_type, indices_addr, data_addr,
                  data_type, nindptr, nelem, num_col)
    ds.push(np.asarray(x.todense(), np.float64), start_row)
    return True


# ---- streaming metadata (reference: LGBM_DatasetInitStreaming /
#      LGBM_DatasetPushRows*WithMetadata / LGBM_DatasetMarkFinished) ----

def dataset_init_streaming(ds: "StreamingDataset", has_weights: int,
                           has_init_scores: int, has_queries: int,
                           nclasses: int) -> bool:
    n = ds.num_total
    ds.fields["label"] = np.zeros(n, np.float64)
    if has_weights:
        ds.fields["weight"] = np.zeros(n, np.float64)
    if has_init_scores:
        ds.fields["init_score"] = np.zeros((n, max(nclasses, 1)) if nclasses > 1
                                           else n, np.float64)
    if has_queries:
        ds._stream_qids = np.zeros(n, np.int64)
    ds._manual_finish = True
    return True


def dataset_push_rows_with_metadata(ds: "StreamingDataset", data_addr: int,
                                    dtype_code: int, nrow: int, ncol: int,
                                    start_row: int, label_addr: int,
                                    weight_addr: int, init_score_addr: int,
                                    query_addr: int) -> bool:
    rows = np.array(_wrap_typed(data_addr, (nrow, ncol), dtype_code),
                    np.float64)
    ds.push(rows, start_row)
    sl = slice(start_row, start_row + nrow)
    if label_addr:
        ds.fields.setdefault("label", np.zeros(ds.num_total, np.float64))[sl] = \
            np.array(_wrap_typed(label_addr, (nrow,), 0))
    if weight_addr:
        ds.fields.setdefault("weight", np.zeros(ds.num_total, np.float64))[sl] = \
            np.array(_wrap_typed(weight_addr, (nrow,), 0))
    if init_score_addr:
        _push_init_scores(ds, init_score_addr, nrow, sl)
    if query_addr:
        if not hasattr(ds, "_stream_qids"):
            ds._stream_qids = np.zeros(ds.num_total, np.int64)
        ds._stream_qids[sl] = np.array(_wrap_typed(query_addr, (nrow,), 2))
    return True


def _push_init_scores(ds, init_score_addr, nrow, sl):
    """Multiclass pushes nrow*k doubles class-major (reference:
    Metadata::InsertInitScores layout)."""
    buf = ds.fields.setdefault("init_score", np.zeros(ds.num_total, np.float64))
    if buf.ndim == 2:
        k = buf.shape[1]
        vals = np.array(_wrap_typed(init_score_addr, (k, nrow), 1))
        buf[sl] = vals.T
    else:
        buf[sl] = np.array(_wrap_typed(init_score_addr, (nrow,), 1))


def dataset_push_rows_by_csr_with_metadata(ds: "StreamingDataset",
                                           indptr_addr: int, indptr_type: int,
                                           indices_addr: int, data_addr: int,
                                           data_type: int, nindptr: int,
                                           nelem: int, num_col: int,
                                           start_row: int, label_addr: int,
                                           weight_addr: int,
                                           init_score_addr: int,
                                           query_addr: int) -> bool:
    x = _wrap_csr(indptr_addr, indptr_type, indices_addr, data_addr,
                  data_type, nindptr, nelem, num_col)
    nrow = x.shape[0]
    ds.push(np.asarray(x.todense(), np.float64), start_row)
    sl = slice(start_row, start_row + nrow)
    if label_addr:
        ds.fields.setdefault("label", np.zeros(ds.num_total, np.float64))[sl] = \
            np.array(_wrap_typed(label_addr, (nrow,), 0))
    if weight_addr:
        ds.fields.setdefault("weight", np.zeros(ds.num_total, np.float64))[sl] = \
            np.array(_wrap_typed(weight_addr, (nrow,), 0))
    if init_score_addr:
        _push_init_scores(ds, init_score_addr, nrow, sl)
    if query_addr:
        if not hasattr(ds, "_stream_qids"):
            ds._stream_qids = np.zeros(ds.num_total, np.int64)
        ds._stream_qids[sl] = np.array(_wrap_typed(query_addr, (nrow,), 2))
    return True


def dataset_mark_finished(ds: "StreamingDataset") -> bool:
    if hasattr(ds, "_stream_qids"):
        qid = ds._stream_qids
        change = np.nonzero(np.diff(qid) != 0)[0] + 1
        bounds = np.concatenate([[0], change, [len(qid)]])
        ds.fields["group"] = np.diff(bounds).astype(np.int64)
    ds._finished = True
    ds.dataset()
    return True


def dataset_set_wait_for_manual_finish(ds: "StreamingDataset",
                                       wait: int) -> bool:
    ds._manual_finish = bool(wait)
    return True


# ---- serialized reference + ByteBuffer (reference:
#      LGBM_DatasetSerializeReferenceToBinary /
#      LGBM_DatasetCreateFromSerializedReference / LGBM_ByteBuffer*) ----

_SCHEMA_MAGIC = b"LGBMTPU-SCHEMA\x01"  # magic + format version byte


def dataset_serialize_reference(ds) -> bytes:
    """Schema-only serialization: bin mappers + names, enough for a remote
    worker to construct a bin-aligned streaming dataset.

    The buffer crosses process/machine boundaries (SynapseML-style hosts
    forward it over the network), so it is inert data — a magic/version
    header, a JSON descriptor and np.savez numeric arrays — never pickled
    code (the reference's counterpart is a plain binary schema dump)."""
    import io
    import json

    ds = _as_dataset(ds)
    ds.construct()
    mappers = ds.binner.mappers
    arrays = {
        "missing_type": np.array([m.missing_type for m in mappers], np.int32),
        "is_categorical": np.array([m.is_categorical for m in mappers],
                                   np.bool_),
        "min_value": np.array([m.min_value for m in mappers], np.float64),
        "max_value": np.array([m.max_value for m in mappers], np.float64),
    }
    for i, m in enumerate(mappers):
        ub = m.upper_bounds if m.upper_bounds is not None else np.zeros(0)
        arrays[f"ub{i}"] = np.asarray(ub, np.float64)
        if m.categories is not None:
            arrays[f"cat{i}"] = np.asarray(m.categories, np.float64)
    header = json.dumps({
        "n_features": len(mappers),
        "feature_names": list(ds.get_feature_name()),
        "params": {k: v for k, v in (ds.params or {}).items()
                   if isinstance(v, (int, float, str, bool))},
    }).encode()
    buf = io.BytesIO()
    np.savez(buf, header=np.frombuffer(header, np.uint8), **arrays)
    return _SCHEMA_MAGIC + buf.getvalue()


def dataset_from_serialized_reference(buf_addr: int, buf_size: int,
                                      num_row: int,
                                      parameters: str) -> "StreamingDataset":
    import io
    import json

    from .binning import BinMapper, DatasetBinner

    raw = bytes((ctypes.c_uint8 * buf_size).from_address(buf_addr))
    if not raw.startswith(_SCHEMA_MAGIC):
        raise ValueError(
            "serialized reference: bad magic or unsupported schema version")
    with np.load(io.BytesIO(raw[len(_SCHEMA_MAGIC):]),
                 allow_pickle=False) as data:
        header = json.loads(bytes(data["header"]).decode())
        mappers = []
        for i in range(int(header["n_features"])):
            mappers.append(BinMapper(
                upper_bounds=data[f"ub{i}"],
                missing_type=int(data["missing_type"][i]),
                is_categorical=bool(data["is_categorical"][i]),
                categories=(data[f"cat{i}"] if f"cat{i}" in data.files
                            else None),
                min_value=float(data["min_value"][i]),
                max_value=float(data["max_value"][i]),
            ))
    schema = Dataset.__new__(Dataset)
    # minimal constructed schema carrier: mappers + names (StreamingDataset
    # only reads binner/feature metadata from its reference)
    n_feat = len(mappers)
    schema.__dict__.update({
        "binner": DatasetBinner(mappers=mappers),
        "feature_names": header["feature_names"],
        "params": dict(header["params"], **_parse_params(parameters)),
        "label": None, "weight": None, "group": None, "init_score": None,
        "position": None, "data": None, "efb": None, "_efb_device": None,
        "_constructed": True, "_num_feature": n_feat,
        "_num_data": 0,
    })
    schema.bins = np.zeros((0, n_feat), np.int16)
    return StreamingDataset(schema, num_row)


# ---- booster model-surgery surface -------------------------------------

def booster_merge(bst: Booster, other: Booster) -> bool:
    """reference: LGBM_BoosterMerge — append other's trees.  Deep-copied:
    later leaf mutations on either booster must not corrupt the other."""
    import copy

    bst._gbdt.models.extend(copy.deepcopy(t) for t in other._gbdt.models)
    return True


def booster_refit_leaf_preds(bst: Booster, leaf_addr: int, nrow: int,
                             ncol: int) -> bool:
    """reference: LGBM_BoosterRefit(leaf_preds) — renew leaf values of each
    tree from the attached training data, rows assigned per the caller's
    leaf-index matrix."""
    from .objectives import create_objective

    leaf = np.array(_wrap_typed(leaf_addr, (nrow, ncol), 2))
    gbdt = bst._gbdt
    ds = bst._train_set
    if ds is None:
        raise ValueError("Refit requires the training dataset to be attached")
    dsc = _as_dataset(ds)
    label = np.asarray(dsc.label, np.float64)
    cfg = gbdt.cfg
    obj = create_objective(cfg)
    k = gbdt.num_tree_per_iteration
    decay = float(cfg.refit_decay_rate)
    import jax.numpy as _jnp

    # start the running score where training did: boost_from_average init
    # scores plus any dataset init_score (reference: RefitTree recomputes
    # gradients at the model's current score, not at zero)
    score = np.zeros((nrow, k), np.float64) if k > 1 else np.zeros(nrow, np.float64)
    if gbdt.init_scores and any(s != 0.0 for s in gbdt.init_scores):
        if k > 1:
            score += np.asarray(gbdt.init_scores, np.float64)[None, :]
        else:
            score += float(gbdt.init_scores[0])
    if dsc.init_score is not None:
        score += np.asarray(dsc.init_score, np.float64).reshape(score.shape)
    # training weights flow through the objective, so the per-leaf g/h sums
    # below aggregate weighted gradients exactly as training did
    w_j = (None if dsc.weight is None
           else _jnp.asarray(np.asarray(dsc.weight), _jnp.float32))
    # compute every renewed leaf table WITHOUT touching the live trees
    # (the loop pays per-iteration device gradient pulls — holding the
    # pack lock through it would stall concurrent serving lookups for
    # the whole refit, exactly what the round-19 _packed redesign keeps
    # off the lock); the sequential score uses the renewed local table,
    # so the math is unchanged
    renewed = []
    v0 = gbdt._pack_version  # structural-mutation guard for the write-back
    for t_i, tree in enumerate(gbdt.models):
        if t_i >= ncol:
            break
        c = t_i % k
        if c == 0:  # gradients refresh once per boosting iteration
            g, h = obj.get_gradients(_jnp.asarray(score, _jnp.float32),
                                     _jnp.asarray(label, _jnp.float32),
                                     w_j)
            g, h = np.asarray(g, np.float64), np.asarray(h, np.float64)
            if g.ndim == 1 and k > 1:
                g, h = g.reshape(k, nrow).T, h.reshape(k, nrow).T
        gc = g[:, c] if g.ndim > 1 else g
        hc = h[:, c] if h.ndim > 1 else h
        li = leaf[:, t_i]
        sum_g = np.bincount(li, weights=gc, minlength=tree.num_leaves)
        sum_h = np.bincount(li, weights=hc, minlength=tree.num_leaves)
        new_vals = -sum_g / (sum_h + cfg.lambda_l2 + 1e-15) * tree.shrinkage
        lv_new = decay * tree.leaf_value + (1.0 - decay) * np.where(
            sum_h > 0, new_vals, tree.leaf_value)
        renewed.append(lv_new)
        pred = lv_new[li]
        if k > 1:
            score[:, c] += pred
        else:
            score += pred
    # write-back + version bump in ONE pack-lock section (round 19): a
    # serving pack build racing this either completes before (consistent
    # pre-refit state) or observes the bump at insert time and rebuilds —
    # it can never cache a half-renewed ensemble under the old version
    with gbdt._plock():
        if gbdt._pack_version != v0:
            raise RuntimeError(
                "the ensemble mutated while LGBM_BoosterRefit ran — the "
                "renewed leaf tables no longer map onto the current "
                "trees; refit aborted, model unchanged")
        for tree, lv_new in zip(gbdt.models, renewed):
            tree.leaf_value = lv_new
        gbdt._invalidate_pred_cache("capi_refit_leaf")  # renewed in place
    return True


def booster_get_leaf_value(bst: Booster, tree_idx: int, leaf_idx: int) -> float:
    return bst.get_leaf_output(tree_idx, leaf_idx)


def booster_set_leaf_value(bst: Booster, tree_idx: int, leaf_idx: int,
                           value: float) -> bool:
    bst.set_leaf_output(tree_idx, leaf_idx, value)
    return True


def booster_get_linear(bst: Booster) -> int:
    return 1 if getattr(bst._gbdt.cfg, "linear_tree", False) else 0


def booster_num_model_per_iteration(bst: Booster) -> int:
    return int(bst.num_model_per_iteration())


def booster_lower_bound(bst: Booster) -> float:
    return float(bst.lower_bound())


def booster_upper_bound(bst: Booster) -> float:
    return float(bst.upper_bound())


def booster_eval_names(bst: Booster):
    """Metric names without evaluating (reference: GetEvalNames is static
    metadata; hosts call it every iteration)."""
    names = []
    for m in bst._gbdt.metrics:
        if m.name in ("ndcg", "map"):
            names.extend(f"{m.name}@{k}" for k in m.cfg.eval_at)
        else:
            names.append(m.name)
    return names


def booster_feature_names(bst: Booster):
    return list(bst.feature_name())


def booster_loaded_param(bst: Booster) -> str:
    import json

    cfg = bst._gbdt.cfg
    return json.dumps({k: v for k, v in cfg.to_dict().items()
                       if isinstance(v, (int, float, str, bool))},
                      default=str)


def booster_validate_feature_names(bst: Booster, names) -> bool:
    model_names = list(bst.feature_name())
    names = list(names)
    if len(names) != len(model_names) or any(
            a != b for a, b in zip(names, model_names)):
        raise ValueError(
            "Expected feature names %r, got %r" % (model_names, names))
    return True


def booster_shuffle_models(bst: Booster, start_iter: int,
                           end_iter: int) -> bool:
    bst.shuffle_models(start_iter, end_iter)
    return True


def booster_get_num_predict(bst: Booster, data_idx: int) -> int:
    gbdt = bst._gbdt
    score = gbdt._score if data_idx == 0 else gbdt._valid_scores[data_idx - 1]
    return int(np.prod(score.shape))


def booster_get_predict_into(bst: Booster, data_idx: int,
                             out_addr: int) -> int:
    """reference: LGBM_BoosterGetPredict — current raw scores of the
    train (0) or (i-1)-th valid dataset."""
    gbdt = bst._gbdt
    score = gbdt._score if data_idx == 0 else gbdt._valid_scores[data_idx - 1]
    out = np.ascontiguousarray(np.asarray(score), np.float64).ravel()
    dest = _wrap(out_addr, (out.size,))
    dest[:] = out
    return int(out.size)


def booster_calc_num_predict(bst: Booster, num_row: int, predict_type: int,
                             start_iteration: int, num_iteration: int) -> int:
    gbdt = bst._gbdt
    k = gbdt.num_tree_per_iteration
    total_iters = len(gbdt.models) // max(k, 1)
    if num_iteration <= 0:
        num_iteration = total_iters - start_iteration
    num_iteration = max(0, min(num_iteration, total_iters - start_iteration))
    if predict_type == _PREDICT_LEAF_INDEX:
        return num_row * num_iteration * k
    if predict_type == _PREDICT_CONTRIB:
        return num_row * k * (bst.num_feature() + 1)
    return num_row * k


def predict_for_file(bst: Booster, data_filename: str, data_has_header: int,
                     predict_type: int, start_iteration: int,
                     num_iteration: int, parameter: str,
                     result_filename: str) -> bool:
    """reference: LGBM_BoosterPredictForFile via Predictor — batch predict a
    data file to a result file, one row per line."""
    from .io.parser import load_data_file

    p = _parse_params(parameter)
    loaded = load_data_file(data_filename, header=bool(data_has_header),
                            label_column=str(p.get("label_column", "")))
    kw = dict(num_iteration=num_iteration if num_iteration > 0 else -1,
              start_iteration=start_iteration)
    if predict_type == _PREDICT_LEAF_INDEX:
        out = bst.predict(loaded["data"], pred_leaf=True, **kw)
    elif predict_type == _PREDICT_CONTRIB:
        out = bst.predict(loaded["data"], pred_contrib=True, **kw)
    elif predict_type == _PREDICT_RAW_SCORE:
        out = bst.predict(loaded["data"], raw_score=True, **kw)
    else:
        out = bst.predict(loaded["data"], **kw)
    out = np.atleast_2d(np.asarray(out, np.float64))
    if out.shape[0] == 1 and len(loaded["data"]) != 1:
        out = out.T
    with open(result_filename, "w") as f:
        for row in out:
            f.write("\t".join(repr(float(v)) for v in np.atleast_1d(row)) + "\n")
    return True


def predict_csr_single_row_into(bst: Booster, indptr_addr: int,
                                indptr_type: int, indices_addr: int,
                                data_addr: int, data_type: int, nindptr: int,
                                nelem: int, num_col: int, predict_type: int,
                                start_iteration: int, num_iteration: int,
                                parameter: str, out_addr: int) -> int:
    x = _wrap_csr(indptr_addr, indptr_type, indices_addr, data_addr,
                  data_type, nindptr, nelem, num_col)
    return _predict_any_into(bst, x, predict_type, out_addr,
                             **_predict_kw(start_iteration, num_iteration,
                                           parameter))


def predict_csr_single_row_fast_init(bst: Booster, predict_type: int,
                                     start_iteration: int, num_iteration: int,
                                     data_type: int, num_col: int,
                                     parameters: str = "") -> _FastConfig:
    cfg = _FastConfig(bst, predict_type, data_type, num_col, parameters)
    cfg.start_iteration = int(start_iteration)
    cfg.num_iteration = int(num_iteration)
    return cfg


def predict_csr_single_row_fast(cfg: _FastConfig, indptr_addr: int,
                                indptr_type: int, indices_addr: int,
                                data_addr: int, nindptr: int, nelem: int,
                                out_addr: int) -> int:
    x = _wrap_csr(indptr_addr, indptr_type, indices_addr, data_addr,
                  cfg.data_type, nindptr, nelem, cfg.ncol)
    return _predict_any_into(cfg.bst, x, cfg.predict_type, out_addr,
                             num_iteration=cfg.num_iteration,
                             start_iteration=cfg.start_iteration,
                             **cfg.kwargs)


# ---- Arrow C-data-interface surface (reference:
#      LGBM_DatasetCreateFromArrow / LGBM_DatasetSetFieldFromArrow /
#      LGBM_BoosterPredictForArrow over include/LightGBM/arrow.h).
#      Chunks arrive as a contiguous array of struct ArrowArray (the C data
#      interface fixed 80-byte layout); pyarrow imports them zero-copy and
#      takes ownership (release is called per the spec). ----

_ARROW_ARRAY_STRUCT_SIZE = 80  # 5 int64 + 5 pointers, fixed by the spec


def _release_arrow_arrays(chunks_addr: int, start: int, n_chunks: int) -> None:
    """Call the C-data-interface release callback on chunks [start, n_chunks)
    that were never imported (the contract transfers ownership to us even on
    failure).  release fn lives at struct offset 64; NULL means already
    released."""
    fn_type = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
    for i in range(start, n_chunks):
        base = chunks_addr + i * _ARROW_ARRAY_STRUCT_SIZE
        fn_addr = ctypes.c_void_p.from_address(base + 64).value
        if fn_addr:
            fn_type(fn_addr)(base)


def _import_arrow_table(n_chunks: int, chunks_addr: int, schema_addr: int):
    import pyarrow as pa

    try:
        schema = pa.Schema._import_from_c(schema_addr)
        struct_type = pa.struct(list(schema))
    except Exception:
        _release_arrow_arrays(chunks_addr, 0, n_chunks)
        raise
    batches = []
    for i in range(n_chunks):
        try:
            arr = pa.Array._import_from_c(
                chunks_addr + i * _ARROW_ARRAY_STRUCT_SIZE, struct_type)
            batches.append(pa.RecordBatch.from_struct_array(arr))
        except Exception:
            _release_arrow_arrays(chunks_addr, i, n_chunks)
            raise
    return pa.Table.from_batches(batches, schema=schema)


def dataset_from_arrow(n_chunks: int, chunks_addr: int, schema_addr: int,
                       parameters: str, reference) -> Dataset:
    table = _import_arrow_table(n_chunks, chunks_addr, schema_addr)
    return Dataset(table, params=_parse_params(parameters),
                   reference=reference if isinstance(reference, Dataset) else None,
                   free_raw_data=False)


def dataset_set_field_from_arrow(ds, field_name: str, n_chunks: int,
                                 chunks_addr: int, schema_addr: int) -> bool:
    import pyarrow as pa

    try:
        dtype = pa.DataType._import_from_c(schema_addr)
    except Exception:
        _release_arrow_arrays(chunks_addr, 0, n_chunks)
        raise
    if n_chunks == 0:
        ds.set_field(field_name, None)  # zero-length clears, like SetField
        return True
    parts = []
    for i in range(n_chunks):
        try:
            parts.append(pa.Array._import_from_c(
                chunks_addr + i * _ARROW_ARRAY_STRUCT_SIZE, dtype))
        except Exception:
            _release_arrow_arrays(chunks_addr, i, n_chunks)
            raise
    vals = np.concatenate([p.to_numpy(zero_copy_only=False) for p in parts])
    ds.set_field(field_name, vals)
    return True


def predict_arrow_into(bst: Booster, n_chunks: int, chunks_addr: int,
                       schema_addr: int, predict_type: int,
                       start_iteration: int, num_iteration: int,
                       parameter: str, out_addr: int) -> int:
    table = _import_arrow_table(n_chunks, chunks_addr, schema_addr)
    return _predict_any_into(bst, table, predict_type, out_addr,
                             **_predict_kw(start_iteration, num_iteration,
                                           parameter))


# ---- network surface (reference: LGBM_NetworkInit / Free /
#      InitWithFunctions).  On TPU the collective transport is XLA over
#      ICI/DCN; these entries configure the machine-list bring-up that
#      parallel/distributed.py maps onto jax.distributed. ----

_NETWORK_PARAMS: dict = {}


def network_init(machines: str, local_listen_port: int, listen_time_out: int,
                 num_machines: int) -> bool:
    _NETWORK_PARAMS.clear()
    if num_machines > 1:
        _NETWORK_PARAMS.update({
            "machines": machines,
            "local_listen_port": int(local_listen_port),
            "time_out": int(listen_time_out),
            "num_machines": int(num_machines),
        })
        from .config import Config
        from .parallel.distributed import init_distributed

        cfg = Config.from_dict(dict(_NETWORK_PARAMS))
        init_distributed(cfg)
    return True


def network_free() -> bool:
    _NETWORK_PARAMS.clear()
    return True


def network_init_with_functions(num_machines: int, rank: int,
                                has_reduce_scatter: int = 0,
                                has_allgather: int = 0) -> bool:
    """reference: LGBM_NetworkInitWithFunctions lets the host (SynapseML)
    supply reduce-scatter/allgather function pointers.  XLA owns the
    collective transport here, so the pointers are not callable into the
    compiled path.  A host that relies on its custom transport (e.g. a
    firewalled environment where only its channel works) would silently get
    XLA collectives instead — so a multi-machine call with real function
    pointers is an ERROR unless the host opts in by setting
    LIGHTGBM_TPU_ACCEPT_XLA_TRANSPORT=1.  Topology (ranks) still drives
    pre_partition semantics.  docs/BINDINGS.md records the deviation."""
    import os

    from .utils.log import log_warning

    _NETWORK_PARAMS.clear()
    if num_machines > 1:
        if (has_reduce_scatter or has_allgather) and os.environ.get(
                "LIGHTGBM_TPU_ACCEPT_XLA_TRANSPORT") != "1":
            raise LightGBMError(
                "LGBM_NetworkInitWithFunctions: the supplied collective "
                "function pointers cannot be invoked from the XLA-compiled "
                "path; collectives would run over XLA's transport instead. "
                "Set LIGHTGBM_TPU_ACCEPT_XLA_TRANSPORT=1 to accept that "
                "substitution (docs/BINDINGS.md).")
        _NETWORK_PARAMS.update({"num_machines": int(num_machines),
                                "rank": int(rank)})
        log_warning(
            "LGBM_NetworkInitWithFunctions: external collective functions are "
            "replaced by XLA collectives on TPU; topology (num_machines=%d, "
            "rank=%d) recorded" % (num_machines, rank))
    return True


def network_params() -> dict:
    """Booster creation merges these (reference: Network state is global)."""
    return dict(_NETWORK_PARAMS)


# ---- global configuration surface --------------------------------------

def dump_param_aliases() -> str:
    """reference: LGBM_DumpParamAliases — JSON of parameter -> aliases."""
    import json

    from .config import _ALIASES

    table: dict = {}
    for alias, canonical in _ALIASES.items():
        table.setdefault(canonical, []).append(alias)
    return json.dumps(table, sort_keys=True)


_MAX_THREADS = [0]  # 0/-1 = OMP default in the reference; advisory here


def get_max_threads() -> int:
    return _MAX_THREADS[0] if _MAX_THREADS[0] > 0 else -1


def set_max_threads(n: int) -> bool:
    """Host-side parallelism cap (reference: LGBM_SetMaxThreads).  Device
    compute is XLA-scheduled; this caps host binning/parsing threads."""
    _MAX_THREADS[0] = int(n)
    return True


_LOG_CALLBACK = [None]


def register_log_callback(fn_addr: int) -> bool:
    """reference: LGBM_RegisterLogCallback(void (*)(const char*))."""
    from .utils import log as _log

    cb = ctypes.CFUNCTYPE(None, ctypes.c_char_p)(fn_addr)
    _LOG_CALLBACK[0] = cb  # keep alive

    class _CRedirect:
        def info(self, msg):
            cb(str(msg).encode())

        warning = info

    _log.register_logger(_CRedirect())
    return True


def get_sample_count(num_total_row: int, parameters: str) -> int:
    p = _parse_params(parameters)
    from .config import Config

    cfg = Config.from_dict(p)
    return int(min(cfg.bin_construct_sample_cnt, num_total_row))


def sample_indices_into(num_total_row: int, parameters: str,
                        out_addr: int) -> int:
    """reference: LGBM_SampleIndices — deterministic row sample for
    sampled-column dataset construction (int32 out)."""
    cnt = get_sample_count(num_total_row, parameters)
    p = _parse_params(parameters)
    from .config import Config

    cfg = Config.from_dict(p)
    rng = np.random.RandomState(cfg.data_random_seed)
    if cnt >= num_total_row:
        idx = np.arange(num_total_row, dtype=np.int32)
    else:
        idx = np.sort(rng.choice(num_total_row, size=cnt,
                                 replace=False)).astype(np.int32)
    dest = (ctypes.c_int32 * len(idx)).from_address(out_addr)
    dest[:] = idx.tolist()
    return len(idx)
