"""Python side of the C API shim (src/capi/lightgbm_tpu_c_api.cpp).

The C layer passes raw pointers as integers; numpy wraps them zero-copy via
ctypes, mirroring the reference's c_api.cpp which operates directly on the
caller's buffers.  Kept deliberately thin: every function takes/returns
plain scalars, strings or Booster objects so the C side needs no numpy ABI.
"""

from __future__ import annotations

import ctypes

import numpy as np

from .basic import Booster

_PREDICT_NORMAL = 0
_PREDICT_RAW_SCORE = 1
_PREDICT_LEAF_INDEX = 2
_PREDICT_CONTRIB = 3


def booster_from_file(filename: str) -> Booster:
    return Booster(model_file=filename)


def booster_from_string(model_str: str) -> Booster:
    return Booster(model_str=model_str)


def num_classes(bst: Booster) -> int:
    return int(getattr(bst._gbdt, "num_tree_per_iteration", 1))


def save_model(bst: Booster, filename: str, start_iteration: int,
               num_iteration: int) -> bool:
    bst.save_model(filename, num_iteration=num_iteration,
                   start_iteration=start_iteration)
    return True


def _wrap(addr: int, shape, dtype=np.float64) -> np.ndarray:
    size = int(np.prod(shape))
    ctype = ctypes.c_double if dtype == np.float64 else ctypes.c_float
    buf = (ctype * size).from_address(addr)
    return np.frombuffer(buf, dtype=dtype).reshape(shape)


def predict_into(bst: Booster, data_addr: int, nrow: int, ncol: int,
                 is_row_major: int, predict_type: int, out_addr: int) -> int:
    if is_row_major:
        x = _wrap(data_addr, (nrow, ncol))
    else:
        x = _wrap(data_addr, (ncol, nrow)).T
    if predict_type == _PREDICT_LEAF_INDEX:
        out = bst.predict(x, pred_leaf=True).astype(np.float64)
    elif predict_type == _PREDICT_CONTRIB:
        out = bst.predict(x, pred_contrib=True)
    elif predict_type == _PREDICT_RAW_SCORE:
        out = bst.predict(x, raw_score=True)
    else:
        out = bst.predict(x)
    out = np.ascontiguousarray(out, np.float64).ravel()
    dest = _wrap(out_addr, (out.size,))
    dest[:] = out
    return int(out.size)
