"""Python side of the C API shim (src/capi/lightgbm_tpu_c_api.cpp).

The C layer passes raw pointers as integers; numpy wraps them zero-copy via
ctypes, mirroring the reference's c_api.cpp which operates directly on the
caller's buffers.  Kept deliberately thin: every function takes/returns
plain scalars, strings or Booster objects so the C side needs no numpy ABI.
"""

from __future__ import annotations

import ctypes

import numpy as np

from .basic import Booster, Dataset

_PREDICT_NORMAL = 0
_PREDICT_RAW_SCORE = 1
_PREDICT_LEAF_INDEX = 2
_PREDICT_CONTRIB = 3

# reference: C_API_DTYPE_* in include/LightGBM/c_api.h
_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}
_CTYPES = {0: ctypes.c_float, 1: ctypes.c_double, 2: ctypes.c_int32, 3: ctypes.c_int64}


def _parse_params(parameters: str) -> dict:
    """reference: Config::Str2Map — 'k1=v1 k2=v2' (space/newline separated)."""
    out = {}
    for tok in parameters.replace("\n", " ").split():
        if "=" not in tok:
            continue
        k, v = tok.split("=", 1)
        for cast in (int, float):
            try:
                v = cast(v)
                break
            except ValueError:
                continue
        if isinstance(v, str):
            # bool-likes must not stay truthy strings ('header=false' would
            # otherwise drop the first data row); mirror Config._coerce
            low = v.lower()
            if low in ("true", "+", "yes"):
                v = True
            elif low in ("false", "-", "no"):
                v = False
        out[k] = v
    return out


def booster_from_file(filename: str) -> Booster:
    return Booster(model_file=filename)


def booster_from_string(model_str: str) -> Booster:
    return Booster(model_str=model_str)


def num_classes(bst: Booster) -> int:
    return int(getattr(bst._gbdt, "num_tree_per_iteration", 1))


def save_model(bst: Booster, filename: str, start_iteration: int,
               num_iteration: int) -> bool:
    bst.save_model(filename, num_iteration=num_iteration,
                   start_iteration=start_iteration)
    return True


def _wrap(addr: int, shape, dtype=np.float64) -> np.ndarray:
    size = int(np.prod(shape))
    ctype = ctypes.c_double if dtype == np.float64 else ctypes.c_float
    buf = (ctype * size).from_address(addr)
    return np.frombuffer(buf, dtype=dtype).reshape(shape)


# -- dataset surface (reference: LGBM_Dataset*) --------------------------

def _wrap_typed(addr: int, shape, dtype_code: int) -> np.ndarray:
    size = int(np.prod(shape))
    buf = (_CTYPES[dtype_code] * size).from_address(addr)
    return np.frombuffer(buf, dtype=_DTYPES[dtype_code]).reshape(shape)


def dataset_from_mat(data_addr: int, dtype_code: int, nrow: int, ncol: int,
                     is_row_major: int, parameters: str, reference) -> Dataset:
    if is_row_major:
        x = _wrap_typed(data_addr, (nrow, ncol), dtype_code)
    else:
        x = _wrap_typed(data_addr, (ncol, nrow), dtype_code).T
    # copy: the Dataset outlives the caller's buffer (reference copies into
    # its own bins during construction as well)
    ds = Dataset(np.array(x, np.float64), params=_parse_params(parameters),
                 reference=reference if isinstance(reference, Dataset) else None,
                 free_raw_data=False)
    return ds


def dataset_from_file(filename: str, parameters: str, reference) -> Dataset:
    from .io.parser import load_data_file

    params = _parse_params(parameters)
    loaded = load_data_file(
        filename,
        header=bool(params.get("header", False)),
        label_column=str(params.get("label_column", "")),
        weight_column=str(params.get("weight_column", "")),
        group_column=str(params.get("group_column", "")),
        ignore_column=str(params.get("ignore_column", "")),
    )
    ds = Dataset(loaded["data"], label=loaded.get("label"),
                 weight=loaded.get("weight"), group=loaded.get("group"),
                 params=params,
                 reference=reference if isinstance(reference, Dataset) else None,
                 free_raw_data=False)
    return ds


def dataset_set_field(ds, field_name: str, data_addr: int,
                      num_element: int, dtype_code: int) -> bool:
    arr = np.array(_wrap_typed(data_addr, (num_element,), dtype_code))
    ds.set_field(field_name, arr)  # Dataset and StreamingDataset both accept
    return True


def dataset_get_num_data(ds) -> int:
    return int(_as_dataset(ds).num_data())


def dataset_get_num_feature(ds) -> int:
    return int(_as_dataset(ds).num_feature())


class StreamingDataset:
    """Push-rows accumulator (reference: LGBM_DatasetCreateByReference +
    LGBM_DatasetPushRows streaming construction).  Rows stream into a
    preallocated buffer; the real Dataset materializes bin-aligned to the
    reference once all rows have arrived."""

    def __init__(self, reference: Dataset, num_total_row: int):
        reference.construct()
        self.reference = reference
        self.num_total = int(num_total_row)
        self.ncol = reference.num_feature()
        self.buf = np.full((self.num_total, self.ncol), np.nan, np.float64)
        self.fields = {}
        self.pushed = 0
        self._ds = None

    def push(self, rows: np.ndarray, start_row: int) -> None:
        n = rows.shape[0]
        self.buf[start_row: start_row + n] = rows
        self.pushed += n

    def set_field(self, name, arr):
        self.fields[name] = arr

    def dataset(self) -> Dataset:
        if self._ds is None:
            if self.pushed < self.num_total:
                raise ValueError(
                    f"only {self.pushed}/{self.num_total} rows pushed")
            self._ds = Dataset(self.buf, reference=self.reference,
                              free_raw_data=False)
            for k, v in self.fields.items():
                self._ds.set_field(k, v)
        return self._ds


def _as_dataset(ds) -> Dataset:
    return ds.dataset() if isinstance(ds, StreamingDataset) else ds


def dataset_create_by_reference(reference: Dataset, num_total_row: int) -> StreamingDataset:
    return StreamingDataset(_as_dataset(reference), num_total_row)


def dataset_push_rows(ds: StreamingDataset, data_addr: int, dtype_code: int,
                      nrow: int, ncol: int, start_row: int) -> bool:
    rows = np.array(_wrap_typed(data_addr, (nrow, ncol), dtype_code), np.float64)
    ds.push(rows, start_row)
    return True


# -- booster training surface (reference: LGBM_Booster*) ------------------

def booster_create(train_set, parameters: str) -> Booster:
    return Booster(params=_parse_params(parameters), train_set=_as_dataset(train_set))


def booster_add_valid(bst: Booster, valid_set) -> bool:
    valid_set = _as_dataset(valid_set)
    name = f"valid_{len(getattr(bst._gbdt, 'valid_sets', []))}"
    bst.add_valid(valid_set, name)
    return True


def booster_update(bst: Booster) -> int:
    # the reference's LGBM_BoosterUpdateOneIter reports is_finished per call;
    # flip the fused path from its deferred (every-32) check to the
    # one-iteration-late async probe
    bst._gbdt._report_finish_every_iter = True
    return 1 if bst.update() else 0


def booster_update_custom(bst: Booster, grad_addr: int, hess_addr: int) -> int:
    n = bst._train_set.num_data() * num_classes(bst)
    grad = np.array(_wrap_typed(grad_addr, (n,), 0), np.float64)
    hess = np.array(_wrap_typed(hess_addr, (n,), 0), np.float64)
    return 1 if bst._gbdt.train_one_iter(grad, hess) else 0


def booster_rollback(bst: Booster) -> bool:
    bst.rollback_one_iter()
    return True


def booster_current_iteration(bst: Booster) -> int:
    return int(bst.current_iteration())


def booster_num_total_model(bst: Booster) -> int:
    return int(bst.num_trees())


def booster_num_feature(bst: Booster) -> int:
    return int(bst.num_feature())


def booster_reset_parameter(bst: Booster, parameters: str) -> bool:
    bst.reset_parameter(_parse_params(parameters))
    return True


def booster_eval_counts(bst: Booster) -> int:
    res = bst.eval_train()
    return len(res)


def booster_get_eval_into(bst: Booster, data_idx: int, out_addr: int) -> int:
    """data_idx 0 = train, i>0 = i-th valid set (reference:
    LGBM_BoosterGetEval)."""
    res = bst.eval_train() if data_idx == 0 else bst.eval_valid()
    if data_idx > 0:
        # filter to the requested valid set (eval_valid returns all); the
        # reference indexes valid sets by REGISTRATION order, and sorting
        # would misorder >=10 auto-named sets ('valid_10' < 'valid_2')
        names = list(getattr(bst._gbdt, "valid_names", []))
        if data_idx - 1 >= len(names):
            return 0  # out-of-range index must not spill all sets' metrics
        want = names[data_idx - 1]
        res = [r for r in res if r[0] == want]
    vals = np.asarray([r[2] for r in res], np.float64)
    dest = _wrap(out_addr, (len(vals),))
    dest[:] = vals
    return len(vals)


def booster_save_string(bst: Booster, start_iteration: int,
                        num_iteration: int) -> str:
    return bst.model_to_string(num_iteration=num_iteration,
                               start_iteration=start_iteration)


def booster_dump_json(bst: Booster, start_iteration: int,
                      num_iteration: int) -> str:
    import json

    return json.dumps(bst.dump_model(num_iteration=num_iteration,
                                     start_iteration=start_iteration),
                      default=float)


def booster_feature_importance_into(bst: Booster, importance_type: int,
                                    out_addr: int) -> int:
    imp = bst.feature_importance("gain" if importance_type == 1 else "split")
    dest = _wrap(out_addr, (len(imp),))
    dest[:] = np.asarray(imp, np.float64)
    return len(imp)


def predict_into(bst: Booster, data_addr: int, nrow: int, ncol: int,
                 is_row_major: int, predict_type: int, out_addr: int) -> int:
    if is_row_major:
        x = _wrap(data_addr, (nrow, ncol))
    else:
        x = _wrap(data_addr, (ncol, nrow)).T
    return _predict_any_into(bst, x, predict_type, out_addr)


# ---- CSR surface (reference: LGBM_DatasetCreateFromCSR /
#      LGBM_BoosterPredictForCSR in src/c_api.cpp) ----

def _wrap_csr(indptr_addr: int, indptr_type: int, indices_addr: int,
              data_addr: int, data_type: int, nindptr: int, nelem: int,
              num_col: int):
    import scipy.sparse as sp

    indptr = np.array(_wrap_typed(indptr_addr, (nindptr,), indptr_type))
    indices = np.array(_wrap_typed(indices_addr, (nelem,), 2))  # int32
    data = np.array(_wrap_typed(data_addr, (nelem,), data_type))
    return sp.csr_matrix((data, indices, indptr),
                         shape=(nindptr - 1, num_col))


def dataset_from_csr(indptr_addr: int, indptr_type: int, indices_addr: int,
                     data_addr: int, data_type: int, nindptr: int,
                     nelem: int, num_col: int, parameters: str,
                     reference) -> Dataset:
    x = _wrap_csr(indptr_addr, indptr_type, indices_addr, data_addr,
                  data_type, nindptr, nelem, num_col)
    return Dataset(x, params=_parse_params(parameters),
                   reference=reference if isinstance(reference, Dataset) else None,
                   free_raw_data=False)


def predict_csr_into(bst: Booster, indptr_addr: int, indptr_type: int,
                     indices_addr: int, data_addr: int, data_type: int,
                     nindptr: int, nelem: int, num_col: int,
                     predict_type: int, out_addr: int) -> int:
    x = _wrap_csr(indptr_addr, indptr_type, indices_addr, data_addr,
                  data_type, nindptr, nelem, num_col)
    return _predict_any_into(bst, x, predict_type, out_addr)


def _predict_any_into(bst: Booster, x, predict_type: int, out_addr: int,
                      **kw) -> int:
    if predict_type == _PREDICT_LEAF_INDEX:
        out = bst.predict(x, pred_leaf=True, **kw).astype(np.float64)
    elif predict_type == _PREDICT_CONTRIB:
        out = bst.predict(x, pred_contrib=True, **kw)
    elif predict_type == _PREDICT_RAW_SCORE:
        out = bst.predict(x, raw_score=True, **kw)
    else:
        out = bst.predict(x, **kw)
    out = np.ascontiguousarray(out, np.float64).ravel()
    dest = _wrap(out_addr, (out.size,))
    dest[:] = out
    return int(out.size)


# ---- single-row fast predict (reference: SingleRowPredictor +
#      LGBM_BoosterPredictForMatSingleRowFast / FastConfigHandle) ----

class _FastConfig:
    """Opaque FastConfig handle: booster + frozen predict settings
    (reference: FastConfig in src/c_api.cpp — caches everything so the
    per-call path only reads one row and writes one result)."""

    def __init__(self, bst: Booster, predict_type: int, data_type: int,
                 ncol: int, parameters: str = ""):
        self.bst = bst
        self.predict_type = predict_type
        self.data_type = data_type
        self.ncol = ncol
        p = _parse_params(parameters)
        self.num_iteration = int(p.pop("num_iteration", -1))
        self.start_iteration = int(p.pop("start_iteration", 0))
        self.kwargs = p  # e.g. predict_disable_shape_check


def predict_single_row_fast_init(bst: Booster, predict_type: int,
                                 data_type: int, ncol: int,
                                 parameters: str = "") -> _FastConfig:
    return _FastConfig(bst, predict_type, data_type, ncol, parameters)


def predict_single_row_fast(cfg: _FastConfig, data_addr: int,
                            out_addr: int) -> int:
    x = np.array(_wrap_typed(data_addr, (1, cfg.ncol), cfg.data_type),
                 np.float64)
    return _predict_any_into(cfg.bst, x, cfg.predict_type, out_addr,
                             num_iteration=cfg.num_iteration,
                             start_iteration=cfg.start_iteration,
                             **cfg.kwargs)


def predict_single_row_into(bst: Booster, data_addr: int, ncol: int,
                            data_type: int, predict_type: int,
                            out_addr: int) -> int:
    x = np.array(_wrap_typed(data_addr, (1, ncol), data_type), np.float64)
    return _predict_any_into(bst, x, predict_type, out_addr)
