"""scikit-learn estimator API.

Reference: python-package/lightgbm/sklearn.py — LGBMModel(BaseEstimator),
LGBMClassifier/LGBMRegressor/LGBMRanker, _ObjectiveFunctionWrapper /
_EvalFunctionWrapper signature adaptation, eval_set handling, fit params.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster, Dataset, LightGBMError
from .engine import train as _train

try:
    from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin
    from sklearn.preprocessing import LabelEncoder

    _SKLEARN = True
except ImportError:  # pragma: no cover
    _SKLEARN = False

    class BaseEstimator:  # type: ignore[no-redef]
        pass

    class ClassifierMixin:  # type: ignore[no-redef]
        pass

    class RegressorMixin:  # type: ignore[no-redef]
        pass


class _ObjectiveFunctionWrapper:
    """Adapt sklearn-signature fobj(y_true, y_pred[, weight, group]) to the
    engine's fobj(score, dataset) (reference: sklearn.py same class)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            return self.func(labels, preds)
        if argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        return self.func(labels, preds, dataset.get_weight(), dataset.get_group())


class _EvalFunctionWrapper:
    """reference: sklearn.py _EvalFunctionWrapper."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            return self.func(labels, preds)
        if argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        return self.func(labels, preds, dataset.get_weight(), dataset.get_group())


class LGBMModel(BaseEstimator):
    """reference: sklearn.py LGBMModel."""

    def __init__(
        self,
        boosting_type: str = "gbdt",
        num_leaves: int = 31,
        max_depth: int = -1,
        learning_rate: float = 0.1,
        n_estimators: int = 100,
        subsample_for_bin: int = 200000,
        objective: Optional[Union[str, Callable]] = None,
        class_weight=None,
        min_split_gain: float = 0.0,
        min_child_weight: float = 1e-3,
        min_child_samples: int = 20,
        subsample: float = 1.0,
        subsample_freq: int = 0,
        colsample_bytree: float = 1.0,
        reg_alpha: float = 0.0,
        reg_lambda: float = 0.0,
        random_state=None,
        n_jobs: Optional[int] = None,
        importance_type: str = "split",
        **kwargs,
    ):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)

    # -- params ----------------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = super().get_params(deep=deep) if _SKLEARN else {}
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for k, v in params.items():
            setattr(self, k, v)
            if k not in self.__init__.__code__.co_varnames:
                self._other_params[k] = v
        return self

    def _process_params(self, default_objective: str) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("importance_type", None)
        params.pop("n_estimators", None)
        params.pop("class_weight", None)
        obj = params.pop("objective", None)
        if callable(obj):
            self._fobj = _ObjectiveFunctionWrapper(obj)
            params["objective"] = "none"
        else:
            self._fobj = None
            params["objective"] = obj or default_objective
        ren = {
            "boosting_type": "boosting",
            "min_split_gain": "min_gain_to_split",
            "min_child_weight": "min_sum_hessian_in_leaf",
            "min_child_samples": "min_data_in_leaf",
            "subsample": "bagging_fraction",
            "subsample_freq": "bagging_freq",
            "colsample_bytree": "feature_fraction",
            "reg_alpha": "lambda_l1",
            "reg_lambda": "lambda_l2",
            "subsample_for_bin": "bin_construct_sample_cnt",
            "random_state": "seed",
            "n_jobs": "num_threads",
        }
        for old, new in ren.items():
            if old in params:
                v = params.pop(old)
                if v is not None:
                    params[new] = v
        if params.get("bagging_fraction", 1.0) < 1.0 and params.get("bagging_freq", 0) == 0:
            params["bagging_freq"] = 1
        if params.get("num_threads") is None:
            params.pop("num_threads", None)
        if params.get("seed") is None:
            params.pop("seed", None)
        params.setdefault("verbosity", -1)
        return params

    # -- fit --------------------------------------------------------------
    def fit(
        self,
        X,
        y,
        sample_weight=None,
        init_score=None,
        group=None,
        eval_set=None,
        eval_names=None,
        eval_sample_weight=None,
        eval_init_score=None,
        eval_group=None,
        eval_metric=None,
        feature_name="auto",
        categorical_feature="auto",
        callbacks=None,
        init_model=None,
    ) -> "LGBMModel":
        params = self._process_params(self._default_objective())
        if eval_metric is not None:
            if callable(eval_metric):
                self._feval = _EvalFunctionWrapper(eval_metric)
            else:
                self._feval = None
                params["metric"] = eval_metric if isinstance(eval_metric, list) else [eval_metric]
        else:
            self._feval = None

        y = np.asarray(y).ravel()
        sw = None if sample_weight is None else np.asarray(sample_weight, np.float64).ravel()
        if self.class_weight is not None and len(np.unique(y)) >= 2:
            from sklearn.utils.class_weight import compute_sample_weight

            cw = compute_sample_weight(self.class_weight, y)
            sw = cw if sw is None else sw * cw

        train_set = Dataset(
            X, label=y, weight=sw, group=group, init_score=init_score,
            feature_name=feature_name, categorical_feature=categorical_feature,
            params=params,
        )
        valid_sets = []
        valid_names = list(eval_names or [])
        if eval_set is not None:
            for i, (vx, vy) in enumerate(eval_set):
                vw = eval_sample_weight[i] if eval_sample_weight else None
                vis = eval_init_score[i] if eval_init_score else None
                vg = eval_group[i] if eval_group else None
                valid_sets.append(
                    Dataset(vx, label=np.asarray(vy).ravel(), weight=vw, group=vg,
                            init_score=vis, reference=train_set, params=params)
                )
                if i >= len(valid_names):
                    valid_names.append(f"valid_{i}")

        if self._fobj is not None:
            params["objective"] = self._fobj
        # record eval curves like the reference wrapper (sklearn.py:
        # LGBMModel.fit wires a record_evaluation callback -> evals_result_)
        self._evals_result = {}
        callbacks = list(callbacks) if callbacks else []
        if valid_sets:
            from .callback import record_evaluation

            callbacks.append(record_evaluation(self._evals_result))
        self._Booster = _train(
            params,
            train_set,
            num_boost_round=self.n_estimators,
            valid_sets=valid_sets,
            valid_names=valid_names,
            feval=self._feval,
            init_model=init_model,
            callbacks=callbacks,
        )
        self._n_features = train_set.num_feature()
        self.n_features_in_ = self._n_features
        self.fitted_ = True
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        return self

    def _default_objective(self) -> str:
        return "regression"

    # -- predict ----------------------------------------------------------
    def predict(self, X, raw_score=False, start_iteration=0, num_iteration=None,
                pred_leaf=False, pred_contrib=False, **kwargs):
        self._check_fitted()
        return self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf, pred_contrib=pred_contrib,
        )

    def _check_fitted(self):
        if not getattr(self, "fitted_", False):
            raise LightGBMError("Estimator not fitted, call fit before exploiting the model.")

    # -- properties --------------------------------------------------------
    @property
    def booster_(self) -> Booster:
        self._check_fitted()
        return self._Booster

    @property
    def best_iteration_(self) -> int:
        self._check_fitted()
        return self._best_iteration

    @property
    def best_score_(self):
        self._check_fitted()
        return self._best_score

    @property
    def evals_result_(self):
        self._check_fitted()
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        self._check_fitted()
        return self._Booster.feature_importance(self.importance_type)

    @property
    def feature_name_(self) -> List[str]:
        self._check_fitted()
        return self._Booster.feature_name()

    @property
    def n_estimators_(self) -> int:
        self._check_fitted()
        return self._Booster.current_iteration()

    @property
    def n_iter_(self) -> int:
        self._check_fitted()
        return self._Booster.current_iteration()


class LGBMRegressor(RegressorMixin, LGBMModel):
    """reference: sklearn.py LGBMRegressor."""

    def _default_objective(self) -> str:
        return "regression"


class LGBMClassifier(ClassifierMixin, LGBMModel):
    """reference: sklearn.py LGBMClassifier (LabelEncoder + predict_proba)."""

    def _prepare_class_labels(self, y) -> np.ndarray:
        """Encode labels and resolve the classification objective; shared
        with the distributed estimators (dask.py)."""
        y = np.asarray(y).ravel()
        self._le = LabelEncoder().fit(y)
        y_enc = self._le.transform(y)
        self.classes_ = self._le.classes_
        self.n_classes_ = len(self.classes_)
        if self.n_classes_ > 2:
            if not callable(self.objective):
                obj = (self.objective
                       if isinstance(self.objective, str) else None)
                if obj is None or obj == "binary":
                    # binary cannot represent >2 classes — promote
                    # (reference wrapper: multiclass switch on n_classes);
                    # callable custom objectives are kept as-is
                    self.objective = "multiclass"
            self._other_params["num_class"] = self.n_classes_
            setattr(self, "num_class", self.n_classes_)
        return y_enc

    def fit(self, X, y, **kwargs) -> "LGBMClassifier":
        y_enc = self._prepare_class_labels(y)
        super().fit(X, y_enc, **kwargs)
        return self

    def _default_objective(self) -> str:
        return "multiclass" if getattr(self, "n_classes_", 2) > 2 else "binary"

    def predict_proba(self, X, raw_score=False, start_iteration=0, num_iteration=None, **kwargs):
        result = super().predict(X, raw_score=raw_score, start_iteration=start_iteration,
                                 num_iteration=num_iteration)
        if raw_score:
            return result
        if result.ndim == 1:
            return np.vstack([1.0 - result, result]).T
        return result

    def predict(self, X, raw_score=False, start_iteration=0, num_iteration=None,
                pred_leaf=False, pred_contrib=False, **kwargs):
        if raw_score or pred_leaf or pred_contrib:
            return super().predict(X, raw_score, start_iteration, num_iteration,
                                   pred_leaf, pred_contrib)
        proba = self.predict_proba(X, start_iteration=start_iteration, num_iteration=num_iteration)
        idx = np.argmax(proba, axis=1)
        return self._le.inverse_transform(idx)


class LGBMRanker(LGBMModel):
    """reference: sklearn.py LGBMRanker (group/eval_group required)."""

    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, group=None, eval_group=None, eval_at=(1, 2, 3, 4, 5), **kwargs) -> "LGBMRanker":
        if group is None:
            raise ValueError("Should set group for ranking task")
        if kwargs.get("eval_set") is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set is not None")
        self._other_params["eval_at"] = list(eval_at)
        setattr(self, "eval_at", list(eval_at))
        super().fit(X, y, group=group, eval_group=eval_group, **kwargs)
        return self
