"""Evaluation metrics.

Reference: src/metric/ (binary_metric.hpp, regression_metric.hpp,
multiclass_metric.hpp, rank_metric.hpp, map_metric.hpp, dcg_calculator.cpp,
xentropy_metric.hpp) and Metric::CreateMetric in src/metric/metric.cpp.

Each metric returns (name, value, is_higher_better) — matching the tuple the
reference's eval framework hands to callbacks.  Computation is numpy/JAX on
the converted scores; distributed evaluation sums (loss, weight) pairs with a
psum in the mesh path (reference: Network::GlobalSyncUpBySum).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .config import Config

EPS = 1e-15


def dcg_at_k(labels_sorted_desc: np.ndarray, k: int, label_gain: np.ndarray) -> float:
    """DCG of the given label order truncated at k (reference:
    DCGCalculator::CalDCGAtK in src/metric/dcg_calculator.cpp)."""
    k = min(k, len(labels_sorted_desc))
    if k <= 0:
        return 0.0
    lab = np.clip(labels_sorted_desc[:k].astype(np.int64), 0, len(label_gain) - 1)
    gains = label_gain[lab]
    discounts = 1.0 / np.log2(np.arange(k, dtype=np.float64) + 2.0)
    return float(np.sum(gains * discounts))


def ndcg_at_k(scores, labels, query_boundaries, k, label_gain) -> float:
    """Mean per-query NDCG@k (reference: NDCGMetric::Eval)."""
    nq = len(query_boundaries) - 1
    total, cnt = 0.0, 0
    for q in range(nq):
        lo, hi = query_boundaries[q], query_boundaries[q + 1]
        ql, qs = labels[lo:hi], scores[lo:hi]
        if np.all(ql == ql[0]):
            total += 1.0  # reference: queries w/o label variation count as 1
            cnt += 1
            continue
        order = np.argsort(-qs, kind="stable")
        d = dcg_at_k(ql[order], k, label_gain)
        ideal = dcg_at_k(np.sort(ql)[::-1], k, label_gain)
        total += d / ideal if ideal > 0 else 1.0
        cnt += 1
    return total / max(cnt, 1)


def _auc(scores: np.ndarray, labels: np.ndarray, weights: Optional[np.ndarray]) -> float:
    """Weighted AUC via rank statistic (reference: AUCMetric in
    binary_metric.hpp — trapezoid over the weighted ROC)."""
    if weights is None:
        weights = np.ones_like(scores, dtype=np.float64)
    order = np.argsort(scores, kind="mergesort")
    s, y, w = scores[order], labels[order], weights[order]
    pos_w = np.where(y > 0, w, 0.0)
    neg_w = np.where(y > 0, 0.0, w)
    # handle ties: group equal scores
    cum_neg = np.cumsum(neg_w)
    total_pos, total_neg = pos_w.sum(), neg_w.sum()
    if total_pos == 0 or total_neg == 0:
        return 1.0
    # For each positive, count negatives with lower score (+ half ties)
    _, inv, counts = np.unique(s, return_inverse=True, return_counts=True)
    grp_neg = np.bincount(inv, weights=neg_w)
    grp_pos = np.bincount(inv, weights=pos_w)
    cum_neg_before = np.concatenate([[0.0], np.cumsum(grp_neg)[:-1]])
    auc = np.sum(grp_pos * (cum_neg_before + 0.5 * grp_neg))
    return float(auc / (total_pos * total_neg))


class Metric:
    name: str = ""
    is_higher_better: bool = False

    def __init__(self, cfg: Config):
        self.cfg = cfg

    def eval(self, pred, label, weight, query_boundaries=None) -> List[Tuple[str, float, bool]]:
        raise NotImplementedError

    # device evaluation protocol (reference: src/metric/cuda/*): metrics
    # returning True from supports_device are evaluated INSIDE one jit per
    # eval set (gbdt.eval_at) — only a scalar crosses to the host, never the
    # (N,) score vector.  `device_eval` returns the pre-`transform` value.
    def supports_device(self, num_class: int) -> bool:
        return False

    def device_eval(self, pred, label, weight):
        raise NotImplementedError

    # rank metrics set True: they are evaluated via device_eval_queries with
    # per-dataset padded-query constants instead of device_eval
    needs_queries = False

    def transform(self, v: float) -> float:
        return v

    # distributed-eval protocol (reference: metrics call
    # Network::GlobalSyncUpBySum on their local sums): decomposable metrics
    # return [(name, local_numerator, local_denominator, higher_better)];
    # global value = transform(sum(num)/sum(den)).  None = not
    # sum-decomposable (the AUC family) — the caller gathers shard
    # predictions instead.
    def eval_sums(self, pred, label, weight, query_boundaries=None):
        return None


def _wmean(vals, weight):
    if weight is None:
        return float(np.mean(vals))
    return float(np.sum(vals * weight) / np.sum(weight))


class _Pointwise(Metric):
    """Pointwise metrics share one elementwise `point` function written
    against an array namespace (numpy on host, jax.numpy on device) so the
    device evaluator (reference: src/metric/cuda/cuda_pointwise_metric.cu)
    and the host path cannot diverge."""

    def point(self, pred, label, xp=np):
        raise NotImplementedError

    def transform(self, v: float) -> float:
        return v

    def eval(self, pred, label, weight, query_boundaries=None):
        v = self.transform(_wmean(self.point(np.asarray(pred), np.asarray(label)), weight))
        return [(self.name, v, self.is_higher_better)]

    def eval_sums(self, pred, label, weight, query_boundaries=None):
        v = self.point(np.asarray(pred), np.asarray(label))
        if weight is None:
            return [(self.name, float(np.sum(v)), float(v.size),
                     self.is_higher_better)]
        return [(self.name, float(np.sum(v * weight)),
                 float(np.sum(weight)), self.is_higher_better)]

    def supports_device(self, num_class: int) -> bool:
        return num_class == 1

    def device_eval(self, pred, label, weight):
        """Weighted mean of `point` as a traced scalar; `transform` is
        applied host-side to the fetched value."""
        import jax.numpy as jnp

        v = self.point(pred, label, xp=jnp)
        if weight is None:
            return jnp.sum(v) / v.shape[0]
        return jnp.sum(v * weight) / jnp.sum(weight)


class L2Metric(_Pointwise):
    name = "l2"

    def point(self, p, y, xp=np):
        return (p - y) ** 2


class RMSEMetric(L2Metric):
    name = "rmse"

    def transform(self, v):
        return float(np.sqrt(v))


class L1Metric(_Pointwise):
    name = "l1"

    def point(self, p, y, xp=np):
        return xp.abs(p - y)


class QuantileMetric(_Pointwise):
    name = "quantile"

    def point(self, p, y, xp=np):
        a = self.cfg.alpha
        d = y - p
        return xp.where(d >= 0, a * d, (a - 1.0) * d)


class HuberMetric(_Pointwise):
    name = "huber"

    def point(self, p, y, xp=np):
        a = self.cfg.alpha
        d = xp.abs(p - y)
        return xp.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairMetric(_Pointwise):
    name = "fair"

    def point(self, p, y, xp=np):
        c = self.cfg.fair_c
        x = xp.abs(p - y)
        return c * x - c * c * xp.log1p(x / c)


class PoissonMetric(_Pointwise):
    name = "poisson"

    def point(self, p, y, xp=np):
        eps = 1e-10
        lp = xp.log(xp.maximum(p, eps))
        return p - y * lp


class GammaMetric(_Pointwise):
    name = "gamma"

    def point(self, p, y, xp=np):
        eps = 1e-10
        x = xp.maximum(p, eps)
        return y / x + xp.log(x)


class GammaDevianceMetric(_Pointwise):
    name = "gamma_deviance"

    def point(self, p, y, xp=np):
        eps = 1e-10
        r = y / xp.maximum(p, eps)
        return 2.0 * (xp.log(xp.maximum(1.0 / xp.maximum(r, eps), eps)) + r - 1.0)


class TweedieMetric(_Pointwise):
    name = "tweedie"

    def point(self, p, y, xp=np):
        rho = self.cfg.tweedie_variance_power
        eps = 1e-10
        x = xp.maximum(p, eps)
        return -y * xp.power(x, 1 - rho) / (1 - rho) + xp.power(x, 2 - rho) / (2 - rho)


class MAPEMetric(_Pointwise):
    name = "mape"

    def point(self, p, y, xp=np):
        return xp.abs(p - y) / xp.maximum(1.0, xp.abs(y))


class BinaryLoglossMetric(_Pointwise):
    name = "binary_logloss"

    def point(self, p, y, xp=np):
        p = xp.clip(p, EPS, 1 - EPS)
        yy = (y > 0).astype(p.dtype)
        return -(yy * xp.log(p) + (1 - yy) * xp.log(1 - p))


class BinaryErrorMetric(_Pointwise):
    name = "binary_error"

    def point(self, p, y, xp=np):
        return ((p > 0.5) != (y > 0)).astype(p.dtype)


def _auc_device(scores, labels, weights):
    """jnp mirror of _auc: tie-grouped weighted rank statistic using
    fixed-shape segment sums (group count bounded by N)."""
    import jax.numpy as jnp

    n = scores.shape[0]
    order = jnp.argsort(scores, stable=True)
    s = scores[order]
    y = labels[order]
    w = jnp.ones_like(s) if weights is None else weights[order].astype(s.dtype)
    pos_w = jnp.where(y > 0, w, 0.0)
    neg_w = w - pos_w
    new_grp = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    gid = jnp.cumsum(new_grp.astype(jnp.int32)) - 1  # (N,) 0-based group ids
    grp_neg = jnp.zeros((n,), s.dtype).at[gid].add(neg_w)
    grp_pos = jnp.zeros((n,), s.dtype).at[gid].add(pos_w)
    cum_neg_before = jnp.concatenate(
        [jnp.zeros((1,), s.dtype), jnp.cumsum(grp_neg)[:-1]]
    )
    tot_pos, tot_neg = jnp.sum(pos_w), jnp.sum(neg_w)
    auc = jnp.sum(grp_pos * (cum_neg_before + 0.5 * grp_neg))
    return jnp.where(
        (tot_pos == 0) | (tot_neg == 0), 1.0,
        auc / jnp.maximum(tot_pos * tot_neg, 1e-30),
    )


class AUCMetric(Metric):
    name = "auc"
    is_higher_better = True

    def eval(self, pred, label, weight, query_boundaries=None):
        return [(self.name, _auc(np.asarray(pred), np.asarray(label), weight), True)]

    def supports_device(self, num_class: int) -> bool:
        return num_class == 1

    def device_eval(self, pred, label, weight):
        return _auc_device(pred, label, weight)


class CrossEntropyMetric(_Pointwise):
    name = "cross_entropy"

    def point(self, p, y, xp=np):
        p = xp.clip(p, EPS, 1 - EPS)
        return -(y * xp.log(p) + (1 - y) * xp.log(1 - p))


class XentLambdaMetric(Metric):
    """reference: CrossEntropyLambdaMetric in xentropy_metric.hpp: the
    lambda-parameterized cross entropy, where a weight scales the intensity
    lambda = w * log1p(e^f) rather than the loss (differs from plain
    xentropy only when weights are present)."""

    name = "xentropy_lambda"

    def eval(self, pred, label, weight, query_boundaries=None):
        p = np.clip(np.asarray(pred, np.float64), EPS, 1 - EPS)
        t = np.asarray(label, np.float64)
        f = np.log(p / (1 - p))
        w = np.ones_like(p) if weight is None else np.asarray(weight, np.float64)
        lam = w * np.log1p(np.exp(f))
        loss = (1 - t) * lam - t * np.log(-np.expm1(-np.maximum(lam, 1e-300)))
        return [(self.name, float(np.mean(loss)), False)]

    def eval_sums(self, pred, label, weight, query_boundaries=None):
        v = self.eval(pred, label, weight)[0][1]
        n = np.shape(label)[0]  # metadata only — no conversion (jaxlint R14)
        return [(self.name, v * n, float(n), False)]


class AucMuMetric(Metric):
    """Multiclass AUC-mu (reference: auc_mu in src/metric/multiclass_metric.hpp,
    Kleiman & Page 2019): average over ordered class pairs (i, j) of the AUC
    separating class i from class j by the decision margin
    pred[:, i] - pred[:, j], optionally weighted by the auc_mu_weights
    misclassification-cost matrix."""

    name = "auc_mu"
    is_higher_better = True

    def __init__(self, cfg=None):
        self.weights = None
        w = list(getattr(cfg, "auc_mu_weights", []) or []) if cfg is not None else []
        if w:
            k = int(round(len(w) ** 0.5))
            if k * k == len(w):
                self.weights = np.asarray(w, np.float64).reshape(k, k)

    def eval(self, pred, label, weight, query_boundaries=None):
        p = np.asarray(pred)
        y = np.asarray(label).astype(np.int64)
        k = p.shape[1]
        total, wsum = 0.0, 0.0
        for i in range(k):
            for j in range(i + 1, k):
                # AUC(i vs j by margin) == AUC(j vs i by -margin): one sort
                # per unordered pair (reference iterates i < j too)
                rows = (y == i) | (y == j)
                if not rows.any() or (y[rows] == i).all() or (y[rows] == j).all():
                    continue
                margin = p[rows, i] - p[rows, j]
                lab = (y[rows] == i).astype(np.float64)
                wrow = None if weight is None else np.asarray(weight)[rows]
                a = _auc(margin, lab, wrow)
                pw = (
                    2.0 if self.weights is None
                    else float(self.weights[i, j] + self.weights[j, i])
                )
                total += pw * a
                wsum += pw
        return [(self.name, total / max(wsum, 1e-30), True)]

    def supports_device(self, num_class: int) -> bool:
        # class pairs unroll in-trace: k*(k-1)/2 masked device AUCs
        return 1 < num_class <= 12

    def device_eval(self, pred, label, weight):
        import jax.numpy as jnp

        k = pred.shape[1]
        y = label.astype(jnp.int32)
        w = (jnp.ones(pred.shape[0], jnp.float32) if weight is None
             else weight.astype(jnp.float32))
        # host parity: pairs skip by LABEL presence (unweighted), computed
        # once per class — zero-weight classes still count (their AUC
        # degenerates to 1.0 in _auc_device exactly like the host's _auc)
        class_present = [jnp.any(y == i) for i in range(k)]
        total = jnp.float32(0.0)
        wsum = jnp.float32(0.0)
        for i in range(k):
            for j in range(i + 1, k):
                # non-pair rows get weight 0 — they sort in but contribute
                # nothing, the fixed-shape analogue of the host's row subset
                pm = ((y == i) | (y == j)).astype(jnp.float32) * w
                lab = (y == i).astype(jnp.float32)
                a = _auc_device(pred[:, i] - pred[:, j], lab, pm)
                pw = (2.0 if self.weights is None
                      else float(self.weights[i, j] + self.weights[j, i]))
                valid = class_present[i] & class_present[j]
                total = total + jnp.where(valid, pw * a, 0.0)
                wsum = wsum + jnp.where(valid, pw, 0.0)
        return total / jnp.maximum(wsum, 1e-30)


class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, pred, label, weight, query_boundaries=None):
        p = np.asarray(pred)  # (N, K)
        y = np.asarray(label).astype(np.int64)
        probs = np.clip(p[np.arange(len(y)), y], EPS, None)
        return [(self.name, _wmean(-np.log(probs), weight), False)]

    def eval_sums(self, pred, label, weight, query_boundaries=None):
        p = np.asarray(pred)
        y = np.asarray(label).astype(np.int64)
        v = -np.log(np.clip(p[np.arange(len(y)), y], EPS, None))
        if weight is None:
            return [(self.name, float(np.sum(v)), float(v.size), False)]
        return [(self.name, float(np.sum(v * weight)),
                 float(np.sum(weight)), False)]

    def supports_device(self, num_class: int) -> bool:
        return num_class > 1

    def device_eval(self, pred, label, weight):
        import jax.numpy as jnp

        y = label.astype(jnp.int32)
        probs = jnp.take_along_axis(pred, y[:, None], axis=1)[:, 0]
        v = -jnp.log(jnp.clip(probs, EPS, None))
        if weight is None:
            return jnp.sum(v) / v.shape[0]
        return jnp.sum(v * weight) / jnp.sum(weight)


class MultiErrorMetric(Metric):
    name = "multi_error"

    def _row_errors(self, pred, label) -> np.ndarray:
        p = np.asarray(pred)
        y = np.asarray(label).astype(np.int64)
        k = self.cfg.multi_error_top_k
        if k <= 1:
            return (np.argmax(p, axis=1) != y).astype(np.float64)
        topk = np.argsort(-p, axis=1)[:, :k]
        return 1.0 - (topk == y[:, None]).any(axis=1).astype(np.float64)

    def eval(self, pred, label, weight, query_boundaries=None):
        return [(self.name, _wmean(self._row_errors(pred, label), weight),
                 False)]

    def eval_sums(self, pred, label, weight, query_boundaries=None):
        e = self._row_errors(pred, label)
        if weight is None:
            return [(self.name, float(np.sum(e)), float(e.size), False)]
        return [(self.name, float(np.sum(e * weight)),
                 float(np.sum(weight)), False)]

    def supports_device(self, num_class: int) -> bool:
        return num_class > 1

    def device_eval(self, pred, label, weight):
        import jax
        import jax.numpy as jnp

        y = label.astype(jnp.int32)
        k = self.cfg.multi_error_top_k
        if k <= 1:
            err = (jnp.argmax(pred, axis=1) != y).astype(jnp.float32)
        else:
            _, topk = jax.lax.top_k(pred, min(k, pred.shape[1]))
            err = 1.0 - jnp.any(topk == y[:, None], axis=1).astype(jnp.float32)
        if weight is None:
            return jnp.sum(err) / err.shape[0]
        return jnp.sum(err * weight) / jnp.sum(weight)


def pad_queries(query_boundaries: np.ndarray):
    """Queries as a dense (Q, S) padded block (the TPU formulation of the
    reference's per-query loops; same layout objectives._RankingObjective
    uses).  Returns (pad_idx, pad_mask)."""
    qb = np.asarray(query_boundaries)
    nq = len(qb) - 1
    lens = np.diff(qb)
    smax = int(lens.max()) if nq else 0
    pad_idx = np.zeros((nq, smax), np.int64)
    pad_mask = np.zeros((nq, smax), bool)
    for q in range(nq):
        lo, hi = qb[q], qb[q + 1]
        pad_idx[q, : hi - lo] = np.arange(lo, hi)
        pad_mask[q, : hi - lo] = True
    return pad_idx, pad_mask


class _MeanPerQuery(Metric):
    """Ranking metrics averaging a per-query statistic decompose for
    distributed eval as (sum over local queries, #local queries).

    Device protocol (reference: the CUDA build's rank metrics,
    src/metric/cuda/cuda_rank_metric.cu): `device_query_constants`
    precomputes per-dataset tensors on host (padding, ideal DCGs);
    `device_eval_queries` is a pure jnp function evaluated inside the
    per-eval-set jit, returning one value per eval_at k."""

    needs_queries = True

    def eval_sums(self, pred, label, weight, query_boundaries=None):
        nq = float(len(query_boundaries) - 1)
        return [(nm, v * nq, nq, hib)
                for nm, v, hib in self.eval(pred, label, weight,
                                            query_boundaries)]

    def supports_device(self, num_class: int) -> bool:
        return num_class == 1

    def device_out_names(self):
        return [f"{self.name}@{k}" for k in self.cfg.eval_at]

    def device_query_constants(self, label: np.ndarray,
                               query_boundaries: np.ndarray,
                               shared: dict = None) -> dict:
        """`shared` (from the evaluator) carries the padded layout computed
        once per eval set: pad_idx/pad_mask as numpy + device arrays."""
        raise NotImplementedError

    def device_eval_queries(self, pred, consts: dict):
        raise NotImplementedError


class NDCGMetric(_MeanPerQuery):
    name = "ndcg"
    is_higher_better = True

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        gains = cfg.label_gain or [float(2**i - 1) for i in range(31)]
        self.label_gain = np.asarray(gains, dtype=np.float64)

    def eval(self, pred, label, weight, query_boundaries=None):
        assert query_boundaries is not None, "ndcg requires query info"
        out = []
        for k in self.cfg.eval_at:
            v = ndcg_at_k(np.asarray(pred), np.asarray(label), query_boundaries, k, self.label_gain)
            out.append((f"ndcg@{k}", v, True))
        return out

    def device_query_constants(self, label, query_boundaries, shared=None):
        import jax.numpy as jnp

        label = np.asarray(label)
        qb = np.asarray(query_boundaries)
        if shared is not None:
            pad_idx, pad_mask = shared["pad_idx_np"], shared["pad_mask_np"]
            dev_idx, dev_mask = shared["pad_idx"], shared["pad_mask"]
        else:
            pad_idx, pad_mask = pad_queries(qb)
            dev_idx, dev_mask = jnp.asarray(pad_idx), jnp.asarray(pad_mask)
        nq = len(qb) - 1
        ks = list(self.cfg.eval_at)
        inv_ideal = np.zeros((len(ks), nq), np.float64)
        all_same = np.zeros(nq, bool)
        for q in range(nq):
            ql = label[qb[q]: qb[q + 1]]
            all_same[q] = bool(np.all(ql == ql[0]))
            ideal = np.sort(ql)[::-1]
            for i, k in enumerate(ks):
                m = dcg_at_k(ideal, min(len(ql), k), self.label_gain)
                inv_ideal[i, q] = 1.0 / m if m > 0 else 0.0
        return {
            "pad_idx": dev_idx,
            "pad_mask": dev_mask,
            "inv_ideal": jnp.asarray(inv_ideal, jnp.float32),
            "all_same": jnp.asarray(all_same),
            "gain_pad": jnp.asarray(  # per-slot gains, masked
                np.where(
                    pad_mask,
                    self.label_gain[np.clip(
                        label[pad_idx].astype(np.int64), 0,
                        len(self.label_gain) - 1)],
                    0.0,
                ), jnp.float32),
            "ks": ks,
        }

    def device_eval_queries(self, pred, consts):
        import jax.numpy as jnp

        idx, msk = consts["pad_idx"], consts["pad_mask"]
        s = pred[idx.reshape(-1)].reshape(idx.shape)
        ms = jnp.where(msk, s, jnp.float32(-1e30))
        order = jnp.argsort(-ms, axis=1, stable=True)
        ranks = jnp.argsort(order, axis=1)  # rank of each original slot
        disc = jnp.where(msk, 1.0 / jnp.log2(ranks.astype(jnp.float32) + 2.0),
                         0.0)
        gains = consts["gain_pad"]
        outs = []
        for i, k in enumerate(consts["ks"]):
            dcg = jnp.sum(gains * disc * (ranks < k), axis=1)  # (Q,)
            # host parity (ndcg_at_k): no-variation or zero-ideal queries
            # count as 1
            valid = (consts["inv_ideal"][i] > 0) & ~consts["all_same"]
            ndcg = jnp.where(valid, dcg * consts["inv_ideal"][i], 1.0)
            outs.append(jnp.mean(ndcg))
        return jnp.stack(outs)


class MAPMetric(_MeanPerQuery):
    name = "map"
    is_higher_better = True

    def eval(self, pred, label, weight, query_boundaries=None):
        assert query_boundaries is not None
        scores, labels = np.asarray(pred), np.asarray(label)
        out = []
        for k in self.cfg.eval_at:
            nq = len(query_boundaries) - 1
            total = 0.0
            for q in range(nq):
                lo, hi = query_boundaries[q], query_boundaries[q + 1]
                order = np.argsort(-scores[lo:hi], kind="stable")
                rel = (labels[lo:hi][order] > 0).astype(np.float64)
                kk = min(k, hi - lo)
                hits = np.cumsum(rel[:kk])
                prec = hits / np.arange(1, kk + 1)
                denom = max(min(int(rel.sum()), kk), 1)
                total += float(np.sum(prec * rel[:kk]) / denom)
            out.append((f"map@{k}", total / max(nq, 1), True))
        return out

    def device_query_constants(self, label, query_boundaries, shared=None):
        import jax.numpy as jnp

        label = np.asarray(label)
        if shared is not None:
            pad_idx, pad_mask = shared["pad_idx_np"], shared["pad_mask_np"]
            dev_idx, dev_mask = shared["pad_idx"], shared["pad_mask"]
        else:
            pad_idx, pad_mask = pad_queries(query_boundaries)
            dev_idx, dev_mask = jnp.asarray(pad_idx), jnp.asarray(pad_mask)
        rel_pad = np.where(pad_mask, label[pad_idx] > 0, False)
        return {
            "pad_idx": dev_idx,
            "pad_mask": dev_mask,
            "rel_pad": jnp.asarray(rel_pad),
            "ks": list(self.cfg.eval_at),
        }

    def device_eval_queries(self, pred, consts):
        import jax.numpy as jnp

        idx, msk = consts["pad_idx"], consts["pad_mask"]
        s = pred[idx.reshape(-1)].reshape(idx.shape)
        ms = jnp.where(msk, s, jnp.float32(-1e30))
        order = jnp.argsort(-ms, axis=1, stable=True)
        srel = jnp.take_along_axis(
            consts["rel_pad"], order, axis=1).astype(jnp.float32)
        hits = jnp.cumsum(srel, axis=1)
        pos = jnp.arange(1, srel.shape[1] + 1, dtype=jnp.float32)[None, :]
        prec = hits / pos
        total_rel = jnp.sum(srel, axis=1)
        outs = []
        for k in consts["ks"]:
            contrib = jnp.sum(prec * srel * (pos <= k), axis=1)
            denom = jnp.maximum(jnp.minimum(total_rel, float(k)), 1.0)
            outs.append(jnp.mean(contrib / denom))
        return jnp.stack(outs)


_METRICS: Dict[str, Callable[[Config], Metric]] = {
    "l2": L2Metric,
    "mse": L2Metric,
    "mean_squared_error": L2Metric,
    "regression": L2Metric,
    "regression_l2": L2Metric,
    "rmse": RMSEMetric,
    "l2_root": RMSEMetric,
    "root_mean_squared_error": RMSEMetric,
    "l1": L1Metric,
    "mae": L1Metric,
    "mean_absolute_error": L1Metric,
    "regression_l1": L1Metric,
    "quantile": QuantileMetric,
    "huber": HuberMetric,
    "fair": FairMetric,
    "poisson": PoissonMetric,
    "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric,
    "mape": MAPEMetric,
    "mean_absolute_percentage_error": MAPEMetric,
    "binary_logloss": BinaryLoglossMetric,
    "binary": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "cross_entropy": CrossEntropyMetric,
    "xentropy": CrossEntropyMetric,
    "auc_mu": AucMuMetric,
    "xentropy_lambda": XentLambdaMetric,
    "multi_logloss": MultiLoglossMetric,
    "multiclass": MultiLoglossMetric,
    "softmax": MultiLoglossMetric,
    "multiclassova": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "ndcg": NDCGMetric,
    "lambdarank": NDCGMetric,
    "rank_xendcg": NDCGMetric,
    "map": MAPMetric,
    "mean_average_precision": MAPMetric,
}

_DEFAULT_METRIC_FOR_OBJECTIVE: Dict[str, str] = {
    "regression": "l2",
    "regression_l1": "l1",
    "huber": "huber",
    "fair": "fair",
    "poisson": "poisson",
    "gamma": "gamma",
    "tweedie": "tweedie",
    "quantile": "quantile",
    "mape": "mape",
    "binary": "binary_logloss",
    "multiclass": "multi_logloss",
    "multiclassova": "multi_logloss",
    "cross_entropy": "cross_entropy",
    "cross_entropy_lambda": "xentropy_lambda",
    "xentlambda": "xentropy_lambda",
    "lambdarank": "ndcg",
    "rank_xendcg": "ndcg",
}


def create_metrics(cfg: Config) -> List[Metric]:
    """reference: Metric::CreateMetric + Config metric-default resolution."""
    names = list(cfg.metric)
    if not names:
        default = _DEFAULT_METRIC_FOR_OBJECTIVE.get(cfg.objective)
        names = [default] if default else []
    out = []
    for name in names:
        # reference: "None"/"na"/"null"/"custom" disable metrics (the alias
        # list in docs/Parameters.rst is case-sensitive only in docs)
        if str(name).lower() in ("none", "null", "na", "custom", ""):
            continue
        if name not in _METRICS:
            raise ValueError(f"Unknown metric: {name}")
        out.append(_METRICS[name](cfg))
    return out
