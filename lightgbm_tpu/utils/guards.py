"""Non-finite guard rails (docs/ROBUSTNESS.md).

Two layers keep NaN/inf out of a boosting run without costing the hot
path anything:

1. **Boundary validation** — labels, weights and init_score are checked
   once, host-side, at ``Dataset`` construction (:func:`validate_finite`).
   O(N) numpy on data the host already holds; a poisoned target fails in
   milliseconds with the offending row index instead of 2000 silently
   constant trees later.

2. **Device-side training guards** — gradients/hessians/split stats can
   still go non-finite mid-run (custom objectives, fp overflow).  The
   guard signal is computed ON DEVICE inside work that is already
   dispatched (O(num_leaves) reductions folded into the growers' round
   bodies / iteration epilogue) and is only PULLED at points where the
   host syncs anyway: the windowed grower folds a finite flag into the
   async info vector it reads one round behind (zero extra dispatches,
   zero blocking syncs — tests/test_retrace.py's budget pin holds with
   guards on), and the full-pass/fast growers accumulate a
   first-bad-iteration scalar checked at the existing deferred sync
   points (the %32 finish probe, eval, flush, save).  Detection can
   therefore lag the corruption by up to 32 iterations on the fastest
   path — the error is ROUND-STAMPED with the iteration the corruption
   entered, which is what makes the lag acceptable.

Host-side ``np.isnan(...)``/``float(...)`` pulls on per-round tensors
inside grower loops are the anti-pattern these layers exist to prevent;
jaxlint R7 (lightgbm_tpu/analysis/rules.py) flags them statically.
"""

from __future__ import annotations

import numpy as np


class NonFiniteError(ValueError):
    """Non-finite data reached training — raised by the boundary
    validators and the device-side guard rails.  Subclasses ValueError so
    generic callers treat it as bad input, which it is."""


def validate_finite(name: str, arr, where: str = "Dataset") -> None:
    """Raise :class:`NonFiniteError` if ``arr`` (None allowed) contains
    NaN/inf, with the count and first offending index in the message."""
    if arr is None:
        return
    a = np.asarray(arr, dtype=np.float64)
    finite = np.isfinite(a)
    if finite.all():
        return
    bad = int(a.size - np.count_nonzero(finite))
    first = int(np.argmin(finite.ravel()))
    kind = "NaN" if np.isnan(a.ravel()[first]) else "inf"
    raise NonFiniteError(
        f"{where} {name} contains {bad} non-finite value(s) "
        f"(first: {kind} at flat index {first} of {a.size}). "
        f"Training on non-finite {name} values silently corrupts every "
        "subsequent boosting round — clean or impute them before "
        "constructing the Dataset (docs/ROBUSTNESS.md). Non-finite "
        "FEATURE values are fine; they take the missing-value path.")
