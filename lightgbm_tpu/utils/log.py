"""Logging with redirect support.

Reference: include/LightGBM/utils/log.h (Log::{Debug,Info,Warning,Fatal},
Log::ResetCallBack) and python-package/lightgbm/basic.py register_logger.
"""

from __future__ import annotations

import logging
from typing import Optional

_logger: Optional[logging.Logger] = None
_info_method = "info"
_warning_method = "warning"
_verbosity = 1


def register_logger(logger: logging.Logger, info_method_name: str = "info",
                    warning_method_name: str = "warning") -> None:
    """Route framework log lines into a user logger (reference:
    lightgbm.register_logger)."""
    global _logger, _info_method, _warning_method
    _logger = logger
    _info_method = info_method_name
    _warning_method = warning_method_name


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = v


def log_debug(msg: str) -> None:
    if _verbosity >= 2:
        _emit(_info_method, f"[LightGBM-TPU] [Debug] {msg}")


def log_info(msg: str) -> None:
    if _verbosity >= 1:
        _emit(_info_method, f"[LightGBM-TPU] [Info] {msg}")


def log_warning(msg: str) -> None:
    if _verbosity >= 0:
        _emit(_warning_method, f"[LightGBM-TPU] [Warning] {msg}")


def log_fatal(msg: str):
    raise RuntimeError(f"[LightGBM-TPU] [Fatal] {msg}")


def _emit(method: str, line: str) -> None:
    if _logger is not None:
        getattr(_logger, method)(line)
    else:
        print(line)
