"""Process-wide graceful kernel degradation.

The Pallas kernels (hist + segment partition) are the TPU hot path, but
their failure mode is all-or-nothing: a Mosaic compile rejection or a
kernel launch failure kills the training run even though a numerically
identical XLA formulation exists for every kernel (ops/histogram.py
onehot/scatter, ops/partition.py::stable_partition_ranges).  Before this
module the only way around a broken kernel was a manual env var
(``LGBMTPU_PARTITION_PALLAS=0``) set by a human after the crash.

Now the dispatchers catch a Pallas failure ONCE, log it through
utils/log.py, and permanently fall back to the XLA path for the rest of
the process:

* :func:`available` is consulted where the ``use_pallas`` statics are
  decided (grower entry points), so later traces compile without the
  broken kernel;
* :func:`disable` records the reason and logs a single warning;
* :func:`is_pallas_failure` classifies an exception so real errors
  (shape bugs, OOM on the XLA side, user errors) still propagate.

The registry is deliberately process-global and never re-enables: a
kernel that failed to compile once will fail again, and flapping between
paths would retrace per tree.  ``reset()`` exists for tests.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from . import locktrace as _lt
from .log import log_warning

# registry keys
HIST = "hist_pallas"
PARTITION = "partition_pallas"
ROUND = "round_pallas"  # the round megakernel (ops/round_pallas.py); its
# fallback is the three-pass fused round, which may still use HIST/PARTITION

_lock = _lt.lock("degrade.registry")
_disabled: Dict[str, str] = {}

# substrings that identify a Pallas/Mosaic kernel failure in exception
# text (case-insensitive).  Deliberately narrow: an arbitrary XLA error
# must NOT trigger a silent fallback (bare "custom_call" would also match
# pure_callback/io_callback failures — excluded).
_SIGNATURES = ("mosaic", "pallas", "tpu custom call", "axon",
               "kernel compile")


def available(feature: str) -> bool:
    with _lock:
        return feature not in _disabled


def disable(feature: str, reason: str) -> None:
    """Permanently (for this process) route ``feature`` to its XLA
    fallback.  Logs once; repeat calls are no-ops."""
    with _lock:
        if feature in _disabled:
            return
        _disabled[feature] = reason
    from ..obs import metrics as _obs  # lazy: keep import graph unchanged

    _obs.counter("degrade_disabled_total").inc()
    _obs.event("degrade", feature=feature, reason=reason[:200])
    log_warning(
        f"Pallas kernel {feature!r} failed and is disabled for this "
        f"process; falling back to the XLA path permanently ({reason}). "
        "See docs/ROBUSTNESS.md — set the matching LGBMTPU_*_PALLAS=0 env "
        "var to skip the attempt entirely on future runs.")


def disabled_reason(feature: str) -> Optional[str]:
    with _lock:
        return _disabled.get(feature)


def is_pallas_failure(exc: BaseException) -> bool:
    """True when ``exc`` looks like a Pallas/Mosaic kernel failure (or an
    injected one from utils/faults.py) rather than a generic error."""
    from .faults import InjectedFault

    if isinstance(exc, InjectedFault):
        return exc.site.startswith("pallas")
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(sig in text for sig in _SIGNATURES)


def describe(exc: BaseException, limit: int = 200) -> str:
    return f"{type(exc).__name__}: {str(exc)[:limit]}"


def run_with_fallback(feature: str, primary, fallback, *,
                      fault_site: Optional[str] = None,
                      surface_errors: bool = False):
    """THE catch-once/degrade-forever pattern, in one place.

    Runs ``primary()`` while ``feature`` is available; a classified
    Pallas failure (or an armed ``fault_site`` injection) disables the
    feature and runs ``fallback()``.  Non-kernel errors always propagate;
    ``surface_errors`` propagates EVERYTHING (correctness harnesses like
    Pallas interpret mode must not silently fall back).  Dispatchers call
    this at trace time (fallback lands inside the trace); grower entry
    wrappers call it at the host level for compile/execute-time failures."""
    if available(feature):
        try:
            if fault_site is not None:
                from . import faults

                faults.maybe_fail(fault_site)
            return primary()
        except Exception as e:  # noqa: BLE001 — classified below
            if surface_errors or not is_pallas_failure(e):
                raise
            disable(feature, describe(e))
    return fallback()


def reset() -> None:
    """Re-enable everything (tests only)."""
    with _lock:
        _disabled.clear()
