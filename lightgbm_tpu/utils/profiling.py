"""Tracing / profiling harness.

Reference: the TIMETAG-gated wall-clock tallies in src/treelearner/*.cpp
(global_timer) and the CLI's per-phase timing logs.  TPU-native analogue:
`jax.profiler` device traces (viewable in TensorBoard/Perfetto) plus a
host-side section timer with the reference's "Time for X: Y s" log style.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional

import jax

from .log import log_info

_section_totals: Dict[str, float] = defaultdict(float)
_section_counts: Dict[str, int] = defaultdict(int)


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a device trace (XLA ops, Pallas kernels, transfers) for the
    enclosed block; open `log_dir` with TensorBoard or Perfetto.
    TPU analogue of nvprof over the reference's CUDA learner."""
    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Label the enclosed dispatches in device traces
    (jax.profiler.TraceAnnotation)."""
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def timed_section(name: str, sync: bool = False) -> Iterator[None]:
    """Host wall-clock tally per section (reference: global_timer's
    start/stop pairs).  With sync=True the section waits for outstanding
    device work first, attributing async dispatch correctly."""
    if sync:
        (jax.device_put(0.0) + 0).block_until_ready()
    t0 = time.perf_counter()
    try:
        with annotate(name):
            yield
    finally:
        dt = time.perf_counter() - t0
        _section_totals[name] += dt
        _section_counts[name] += 1


def log_timings(reset: bool = True) -> Dict[str, float]:
    """Emit the accumulated section tallies (reference: the TIMETAG summary
    printed at the end of training)."""
    out = dict(_section_totals)
    for name in sorted(_section_totals, key=_section_totals.get, reverse=True):
        log_info(
            f"Time for {name}: {_section_totals[name]:.6f} s "
            f"({_section_counts[name]} calls)"
        )
    if reset:
        _section_totals.clear()
        _section_counts.clear()
    return out
