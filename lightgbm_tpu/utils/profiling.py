"""Tracing / profiling harness.

Reference: the TIMETAG-gated wall-clock tallies in src/treelearner/*.cpp
(global_timer) and the CLI's per-phase timing logs.  TPU-native analogue:
`jax.profiler` device traces (viewable in TensorBoard/Perfetto) plus a
host-side section timer with the reference's "Time for X: Y s" log style.

Section tallies live in the process-wide metrics registry
(``lightgbm_tpu/obs``) as ``section_seconds.<name>`` histograms — one
thread-safe store shared with the rest of the telemetry layer, replacing
the module-global dicts this module carried before round 10 (they raced
under concurrent sections and were invisible to metrics snapshots).
``log_timings`` reads and (optionally) clears them; they also appear in
every ``metrics_file=`` snapshot and the ``python -m lightgbm_tpu.obs``
dump.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Dict, Iterator

import jax
import numpy as np

from ..obs import metrics as _obs
from ..obs import trace as _trace
from .log import log_info


def _drain_device_queue() -> None:
    """Honest wait for outstanding device work: a HOST PULL of a tiny fresh
    value, which cannot resolve until the device queue drains to it.
    ``block_until_ready()`` is NOT used — PERF_NOTES/NEXT.md record it
    returning EARLY through the axon tunnel before the async pipeline
    drains, which silently mis-attributed every sync=True section."""
    np.asarray(jax.device_put(0.0) + 0)


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a device trace (XLA ops, Pallas kernels, transfers) for the
    enclosed block; open `log_dir` with TensorBoard or Perfetto.
    TPU analogue of nvprof over the reference's CUDA learner."""
    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Label the enclosed dispatches in device traces
    (jax.profiler.TraceAnnotation)."""
    with jax.profiler.TraceAnnotation(name):
        yield


def _jax_annotation_factory(name: str, attrs: dict):
    """obs/trace.py annotation factory: spans carrying a ``step``/
    ``iteration`` attribute mirror into StepTraceAnnotation (so the
    profiler's step view lines up with boosting iterations), everything
    else into TraceAnnotation."""
    step = attrs.get("step", attrs.get("iteration"))
    if step is not None:
        return jax.profiler.StepTraceAnnotation(name, step_num=int(step))
    return jax.profiler.TraceAnnotation(name)


def install_jax_annotations() -> None:
    """Mirror every context-manager span (obs/trace.py) into jax.profiler
    annotations, lining host spans up with on-chip XLA traces captured via
    :func:`device_trace`.  The obs package itself stays stdlib-only: THIS
    module (which already imports jax) owns the bridge, and it is
    installed automatically when ``LGBMTPU_JAX_PROFILER=1`` — the layers
    that open spans (models/gbdt.py, engine) import this module, so the
    env opt-in needs no further wiring."""
    _trace.set_annotation_factory(_jax_annotation_factory)


if os.environ.get("LGBMTPU_JAX_PROFILER") == "1":
    install_jax_annotations()


@contextlib.contextmanager
def timed_section(name: str, sync: bool = False) -> Iterator[None]:
    """Host wall-clock tally per section (reference: global_timer's
    start/stop pairs).  With sync=True the section first drains outstanding
    device work through the documented host-pull sync, attributing async
    dispatch correctly.  Without sync, the tally measures HOST time only —
    async device work dispatched inside the section may still be in flight
    when it closes (jaxlint R9 flags the raw-perf_counter form of that
    mistake)."""
    if sync:
        _drain_device_queue()
    t0 = time.perf_counter()
    try:
        with annotate(name):
            yield
    finally:
        dt = time.perf_counter() - t0
        # always=True: entering a timed_section IS the opt-in — the tally
        # must not go silent under telemetry=false (the pre-round-10
        # module-global tallies recorded unconditionally too)
        _obs.histogram(f"{_obs.SECTION_PREFIX}{name}").observe(
            dt, always=True)


def log_timings(reset: bool = True) -> Dict[str, float]:
    """Emit the accumulated section tallies (reference: the TIMETAG summary
    printed at the end of training).  Returns {section: total_seconds}."""
    sections = _obs.histogram_items(_obs.SECTION_PREFIX)
    out = {}
    for full_name, h in sections.items():
        name = full_name[len(_obs.SECTION_PREFIX):]
        out[name] = h.total
    for name in sorted(out, key=out.get, reverse=True):
        h = sections[_obs.SECTION_PREFIX + name]
        log_info(f"Time for {name}: {h.total:.6f} s ({h.count} calls)")
    if reset:
        _obs.clear_prefix(_obs.SECTION_PREFIX)
    return out
