"""Deterministic, env-gated fault injection — the harness every recovery
test drives (docs/ROBUSTNESS.md).

A production boosting run dies in a handful of well-understood ways: the
host process is preempted mid-round, a snapshot write is cut short, a
remote Mosaic/Pallas compile fails, an SPMD worker dies, or a custom
objective emits NaN gradients.  Each of those failure classes has an
injection SITE wired into the runtime; arming a site is purely
environmental, so the library code under test is byte-identical to
production code:

    LGBMTPU_FAULT=<site>:<round>[,<site>:<round>...]

Rank-gated sites additionally accept the inline three-field form
``<site>:<rank>:<round>`` (``worker_hang:1:3`` = rank 1 hangs at round 3),
equivalent to setting ``LGBMTPU_FAULT_RANK`` for that one site.

Sites (see docs/ROBUSTNESS.md for the exact trigger points):

``host_crash``      engine.train round loop — hard process exit
                    (``os._exit``) at the START of 1-based boosting
                    iteration <round>.
``worker_hang``     same trigger point — the process SLEEPS FOREVER
                    instead of dying, modelling a rank wedged inside a
                    collective: exit-code watchdogs never fire, only the
                    heartbeat watchdog catches it.  Rank-gated.
``snapshot_write``  utils/checkpoint.py atomic writer — hard process exit
                    mid-write (after a partial payload is flushed to the
                    TEMP file, before ``os.replace``) for the snapshot
                    covering iteration <round>.
``manifest_write``  utils/checkpoint.py fleet-checkpoint writer — hard
                    process exit BETWEEN the rank-0 snapshot landing and
                    the fleet manifest publish: the torn-fleet-state
                    window the manifest protocol exists to exclude.
``continual_swap``  lightgbm_tpu/continual/runtime.py rollover — hard
                    process exit BETWEEN the update's durable checkpoint
                    (raw-delta snapshot + manifest) and its publication
                    through ``ServingRuntime.swap_model``: the previous
                    ensemble keeps serving, no torn pack is ever
                    published, and a resumed runner picks the update up
                    from the manifest.  <round> is the rollover sequence
                    number (1-based).
``worker_death``    parallel/launcher.py worker body — hard process exit at
                    the start of iteration <round>, gated to one rank via
                    ``LGBMTPU_FAULT_RANK`` (compared against the worker's
                    ``LIGHTGBM_TPU_RANK``).
``pallas_hist``     the histogram dispatcher (ops/histogram.py) — raises
                    :class:`InjectedFault` at trace time, modelling a
                    remote Mosaic kernel-compile failure.  <round> counts
                    dispatcher CALLS (0 = first).
``pallas_partition``ops/partition.py::partition_rows — same semantics.
``pallas_round``    ops/treegrow_windowed.py::grow_tree_windowed's round-
                    megakernel attempt — same semantics; exercises the
                    ROUND layer of the degradation net (fallback = the
                    three-pass fused round).
``nonfinite_grad``  models/gbdt.py — poisons gradient element 0 with NaN at
                    1-based boosting iteration <round>.
``nonfinite_hess``  same, for the hessian.

Serve-side sites (round 22 — the chaos harness for the replica fleet,
``serve/fleet.py``; all four are CALL-counted like the pallas sites, and
each replica batch touches a site at two pipeline stages — stage A on
batch receipt, stage B after the dispatch retires — so even/odd <round>
values select the stage):

``replica_dispatch`` a replica's batch dispatch raises
                    :class:`InjectedFault` — the transient failure class
                    (driver hiccup, OOM on one device): the batch's
                    requests requeue EXACTLY once onto a healthy replica.
``replica_death``   the replica worker THREAD dies (the thread-fleet
                    analogue of ``worker_death``): its in-flight batch
                    requeues and the fleet supervisor restarts the
                    replica with backoff.
``replica_hang``    the replica thread SLEEPS FOREVER mid-pipeline —
                    only the per-replica heartbeat watchdog catches it;
                    the supervisor requeues the wedged batch and spawns
                    a replacement.
``swap_publish``    ``ServingRuntime.swap_model`` — raises BETWEEN the
                    replacement pack's warm build and its publication to
                    the model table: every replica must keep serving the
                    OLD ensemble, never a torn table.

Determinism rules:

* a (site, round) pair fires exactly ONCE per process (an in-memory
  registry); crash sites never return.
* with ``LGBMTPU_FAULT_ONCE_DIR=<dir>`` set, firing also drops a marker
  file, making the once-only guarantee hold ACROSS processes — the knob
  that lets a relaunched worker (or a watchdog restart) run clean while
  the first attempt faulted.  parallel/launcher.py sets it automatically
  for its workers when a fault is armed.
* rank-gated sites only fire when ``LGBMTPU_FAULT_RANK`` is unset or
  matches ``LIGHTGBM_TPU_RANK``.

Nothing here imports jax: injection must work in thin subprocesses (the
launcher watchdog tests) without paying a backend bring-up.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional, Tuple

# exit code for injected hard crashes — distinctive enough that a watchdog
# log or a test can tell an injected death from a real one
CRASH_EXIT_CODE = 113

_RANK_GATED_SITES = ("worker_death", "worker_hang")

# sites whose <round> is a per-site CALL counter rather than an explicit
# round number passed by the caller (trace-time sites have no round; the
# serve sites count pipeline-stage touches — see the module docstring)
_CALL_COUNTED_SITES = ("pallas_hist", "pallas_partition", "pallas_round",
                       "replica_dispatch", "replica_death", "replica_hang",
                       "swap_publish")


class InjectedFault(RuntimeError):
    """Raised by :func:`maybe_fail` when an armed site fires."""

    def __init__(self, site: str, round_i: int):
        super().__init__(
            f"injected fault at site {site!r} (round {round_i}) — "
            "LGBMTPU_FAULT test harness, not a real failure")
        self.site = site
        self.round_i = round_i


_spec_cache: Tuple[Optional[str], Dict[str, int], Dict[str, str]] = (
    None, {}, {})
_fired: set = set()
_call_counts: Dict[str, int] = {}


def _parse_full(raw: str) -> Tuple[Dict[str, int], Dict[str, str]]:
    """``"site:round,site:rank:round"`` -> ({site: round}, {site: rank}).
    Malformed entries raise ValueError immediately — a typo'd fault spec
    silently arming nothing would invalidate the test that set it."""
    rounds: Dict[str, int] = {}
    ranks: Dict[str, str] = {}
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) == 2 and parts[0]:
            site, rnd = parts
        elif len(parts) == 3 and parts[0]:
            # inline rank gate: <site>:<rank>:<round> (rank-gated sites)
            site, rank, rnd = parts
            ranks[site] = str(int(rank))
        else:
            raise ValueError(
                f"malformed LGBMTPU_FAULT entry {entry!r}: want "
                "<site>:<round> or <site>:<rank>:<round>")
        rounds[site] = int(rnd)
    return rounds, ranks


def parse_spec(raw: Optional[str] = None) -> Dict[str, int]:
    """``"site:round,site:round"`` -> {site: round} (rank qualifiers in the
    three-field form are validated and dropped here; :func:`_spec_ranks`
    carries them)."""
    if raw is None:
        raw = os.environ.get("LGBMTPU_FAULT", "")
    return _parse_full(raw)[0]


def _refresh_spec() -> None:
    global _spec_cache  # jaxlint: disable=R5 (host-side env-spec memo; fault arming is DELIBERATELY a trace-time decision for the pallas sites and a host decision everywhere else — nothing here touches traced values)
    raw = os.environ.get("LGBMTPU_FAULT", "")
    if _spec_cache[0] != raw:
        rounds, ranks = _parse_full(raw)
        _spec_cache = (raw, rounds, ranks)


def _spec() -> Dict[str, int]:
    _refresh_spec()
    return _spec_cache[1]


def _spec_ranks() -> Dict[str, str]:
    _refresh_spec()
    return _spec_cache[2]


def _this_rank() -> str:
    """The rank a fault gate compares against: the fleet-GLOBAL worker
    id when the launcher set one (multi-slice fleets reuse slice-LOCAL
    rendezvous ranks per slice, so LIGHTGBM_TPU_RANK alone would fire
    the fault in every slice at once), else LIGHTGBM_TPU_RANK."""
    return os.environ.get("LGBM_TPU_WORKER_ID",
                          os.environ.get("LIGHTGBM_TPU_RANK", ""))


def _rank_allows(site: str) -> bool:
    inline = _spec_ranks().get(site)
    if inline is not None:
        # inline <site>:<rank>:<round> form wins over the env gate
        return _this_rank() == inline
    if site not in _RANK_GATED_SITES:
        return True
    want = os.environ.get("LGBMTPU_FAULT_RANK")
    if want is None:
        return True
    return _this_rank() == want


def _once_marker(site: str, round_i: int) -> Optional[str]:
    d = os.environ.get("LGBMTPU_FAULT_ONCE_DIR")
    if not d:
        return None
    return os.path.join(d, f"lgbmtpu_fault_{site}_{round_i}.fired")


def armed(site: str) -> bool:
    """True when the env spec arms ``site`` at ANY round — lets hot paths
    skip injection scaffolding (e.g. the snapshot writer's split-write)
    entirely when no fault is armed."""
    return site in _spec()


def fire(site: str, round_i: Optional[int] = None) -> bool:
    """True exactly once when ``site`` is armed for this round.

    ``round_i`` is the caller's 1-based round for round-stamped sites;
    call-counted sites (trace-time Pallas sites) pass None and match on
    the per-site call counter instead."""
    spec = _spec()
    if site not in spec:
        return False
    if round_i is None:
        if site not in _CALL_COUNTED_SITES:
            raise ValueError(f"site {site!r} needs an explicit round")
        round_i = _call_counts.get(site, 0)
        _call_counts[site] = round_i + 1
    if spec[site] != round_i:
        return False
    if not _rank_allows(site):
        return False
    key = (site, round_i)
    if key in _fired:
        return False
    marker = _once_marker(site, round_i)
    if marker is not None and os.path.exists(marker):
        return False
    _fired.add(key)
    if marker is not None:
        try:
            with open(marker, "w") as fh:
                fh.write(f"{os.getpid()}\n")
        except OSError:
            pass  # marker is best-effort; in-process registry still holds
    # telemetry (lazy import: this module must stay importable without the
    # package's jax-importing __init__ cost mattering — obs is stdlib-only).
    # Crash sites record BEFORE dying, so the event reaches the JSONL sink
    # (the in-memory ring dies with the process, the file line survives).
    from ..obs import metrics as _obs

    _obs.counter("faults_injected_total").inc()
    _obs.event("fault", site=site, round=round_i)
    return True


def maybe_crash(site: str, round_i: Optional[int] = None) -> None:
    """Hard, unclean process death — no atexit, no finally blocks, no
    flushing: the closest a test can get to a preemption."""
    if fire(site, round_i):
        # make the death visible in worker logs before dying unflushed
        print(f"[LightGBM-TPU] [Fault] injected {site} crash "
              f"(round {round_i})", flush=True)
        os._exit(CRASH_EXIT_CODE)


def maybe_hang(site: str, round_i: Optional[int] = None) -> None:
    """Sleep FOREVER when the site fires — the wedged-in-a-collective
    failure class (a rank stuck in an all-reduce never exits, so exit-code
    watchdogs never fire; only heartbeat staleness catches it).  The fault
    event and the cross-process once-marker are written by :func:`fire`
    BEFORE the hang, so a watchdog relaunch runs clean."""
    if fire(site, round_i):
        print(f"[LightGBM-TPU] [Fault] injected {site} hang "
              f"(round {round_i}) — sleeping forever", flush=True)
        while True:
            time.sleep(3600)


def maybe_fail(site: str, round_i: Optional[int] = None) -> None:
    """Raise :class:`InjectedFault` when the site fires (kernel-failure
    sites — the degradation path in utils/degrade.py recognizes it)."""
    if fire(site, round_i):
        raise InjectedFault(site, round_i if round_i is not None else -1)


def corrupt_nonfinite(site: str, round_i: int, arr):
    """Return ``arr`` with element 0 set to NaN when the site fires —
    the non-finite-gradient failure class for the guard-rail tests.
    Device arrays stay device arrays (jnp ``.at[]`` update)."""
    if not fire(site, round_i):
        return arr
    import numpy as np

    if hasattr(arr, "at"):  # jax array
        return arr.at[(0,) * arr.ndim].set(np.nan)
    arr = np.asarray(arr, dtype=np.float64).copy()
    arr[(0,) * arr.ndim] = np.nan
    return arr


def reset() -> None:
    """Clear the fired registry and call counters (tests only; marker
    files in LGBMTPU_FAULT_ONCE_DIR are the caller's to clean)."""
    global _spec_cache
    _fired.clear()
    _call_counts.clear()
    _spec_cache = (None, {}, {})
