"""Runtime retrace/donation sanitizer.

The static pass (``lightgbm_tpu/analysis``, jaxlint R2) catches recompile
hazards visible in the AST; *varying* static arguments and shape drift are
runtime properties.  This module turns them into executable assertions: a
process-global ``jax.monitoring`` listener counts every jaxpr trace and every
XLA backend compile, and :class:`CompileCounter` exposes deltas so a test can
pin "N boosting rounds at fixed shape compile exactly once" (the per-round
recompile class docs/NEXT.md suspects in the windowed admit phase).

Counting is cumulative and process-wide — the listener is installed once and
never removed (``jax.monitoring`` has no unregister; ``clear_event_listeners``
would nuke listeners we don't own).  Counters snapshot on ``__enter__`` and
report deltas, so nesting and interleaving are safe.

Donation side: XLA silently ignores ``donate_argnums`` on platforms without
buffer aliasing (CPU warns and copies), so "the windowed grower donates its
state" is only true where donation is supported.  :func:`donation_consumed`
reports whether a donated input was actually invalidated, and
:func:`assert_donation_consumed` asserts it on platforms that support
donation while degrading to a no-op where XLA ignores it — tests stay green
on the CPU tier-1 mesh and bite on device.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional

import jax

COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"

_lock = threading.Lock()
_counts = {"compiles": 0, "traces": 0}
_installed = False


def _listener(event: str, duration: float, **_kw) -> None:  # noqa: ARG001
    if event == COMPILE_EVENT:
        with _lock:
            _counts["compiles"] += 1
    elif event == TRACE_EVENT:
        with _lock:
            _counts["traces"] += 1


def _install() -> None:
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    jax.monitoring.register_event_duration_secs_listener(_listener)


def compile_totals() -> dict:
    """Cumulative (process-lifetime) compile/trace counts since install."""
    _install()
    with _lock:
        return dict(_counts)


class RetraceError(AssertionError):
    """A jit compiled/retraced more than the test's contract allows."""


class CompileCounter:
    """Context manager counting XLA backend compiles and jaxpr traces in the
    enclosed block.

    >>> with CompileCounter() as c:
    ...     train_some_rounds()
    >>> assert c.compiles == 0  # everything was warm

    ``compiles`` counts backend (HLO -> executable) compiles: the expensive
    event, and the one "exactly one compile per (shape, dtype) config" pins.
    ``traces`` counts jaxpr traces: cheaper, but a per-round retrace that
    hits the persistent compile cache still shows up here.
    """

    def __init__(self) -> None:
        self._c0: Optional[int] = None
        self._t0: Optional[int] = None

    def __enter__(self) -> "CompileCounter":
        _install()
        with _lock:
            self._c0 = _counts["compiles"]
            self._t0 = _counts["traces"]
        return self

    def __exit__(self, *exc) -> None:
        return None

    @property
    def compiles(self) -> int:
        with _lock:
            return _counts["compiles"] - self._c0

    @property
    def traces(self) -> int:
        with _lock:
            return _counts["traces"] - self._t0

    def assert_compiles(self, expected: int, what: str = "block") -> None:
        got = self.compiles
        if got != expected:
            raise RetraceError(
                f"{what}: expected exactly {expected} backend compile(s), "
                f"observed {got} (traces: {self.traces}) — a static arg or "
                "shape is varying per call; see docs/ANALYSIS.md")

    def assert_no_recompile(self, what: str = "block") -> None:
        """The steady-state contract: zero compiles AND zero traces —
        every dispatch in the block hit a warm jit cache."""
        got_c, got_t = self.compiles, self.traces
        if got_c or got_t:
            raise RetraceError(
                f"{what}: expected a warm cache but observed {got_c} "
                f"compile(s) / {got_t} trace(s) — something retraces per "
                "call (varying static arg, new closure identity, or shape "
                "drift); see docs/ANALYSIS.md")


def expect_compiles(expected: int, what: str = "block") -> "_ExpectCompiles":
    """``with expect_compiles(1): ...`` — raises RetraceError on mismatch."""
    return _ExpectCompiles(expected, what)


class _ExpectCompiles(CompileCounter):
    def __init__(self, expected: int, what: str) -> None:
        super().__init__()
        self._expected = expected
        self._what = what

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.assert_compiles(self._expected, self._what)
        return None


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------

def donation_supported() -> bool:
    """Whether the default backend honors donate_argnums (CPU ignores it)."""
    return jax.default_backend() in ("tpu", "gpu")


def donation_consumed(*arrays) -> bool:
    """True when every given donated INPUT buffer was actually invalidated
    by the call it was donated to (``Array.is_deleted``)."""
    return all(getattr(a, "is_deleted", lambda: False)() for a in arrays)


def assert_donation_consumed(arrays: Iterable, what: str = "donated state"
                             ) -> None:
    """Assert donated inputs were consumed — i.e. the donation actually
    took (the donated jit aliased the buffers) AND the caller cannot be
    holding a live reference it might read after the call.  No-op on
    platforms where XLA ignores donation."""
    if not donation_supported():
        return
    arrays = list(arrays)
    if not donation_consumed(*arrays):
        alive = sum(1 for a in arrays
                    if not getattr(a, "is_deleted", lambda: False)())
        raise AssertionError(
            f"{what}: {alive}/{len(arrays)} donated buffer(s) still alive "
            "after the call — donation was dropped (aliasing rejected) or "
            "the state is not threaded linearly (jaxlint R3)")
