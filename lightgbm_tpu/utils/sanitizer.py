"""Runtime retrace/donation/dispatch sanitizer.

The static pass (``lightgbm_tpu/analysis``, jaxlint R2/R6) catches recompile
and dispatch-structure hazards visible in the AST; *varying* static
arguments, shape drift, and the actual per-round dispatch/sync traffic are
runtime properties.  This module turns them into executable assertions: a
process-global ``jax.monitoring`` listener counts every jaxpr trace and every
XLA backend compile, and :class:`CompileCounter` exposes deltas so a test can
pin "N boosting rounds at fixed shape compile exactly once" (the per-round
recompile class docs/NEXT.md suspects in the windowed admit phase).

Dispatch side (round 7): host round loops that dispatch jitted work record
each dispatch through :func:`record_dispatch` and route every host read of
device data through :func:`sync_pull` (a BLOCKING pull — the ~45 ms tunnel
round-trip class) or the :func:`async_pull_start`/:func:`async_pull_result`
pair (a pipelined read that overlaps device compute and never stalls the
device queue).  :class:`DispatchCounter` snapshots all of it, so "each
steady-state windowed round is exactly ONE dispatch and ZERO blocking
syncs" is an executable invariant (tests/test_retrace.py), not benchmark
archaeology — and :meth:`DispatchCounter.assert_round_budget` is the gate
the grower itself arms under ``LGBMTPU_DISPATCH_BUDGET=1``.

Counting is cumulative and process-wide — the listener is installed once and
never removed (``jax.monitoring`` has no unregister; ``clear_event_listeners``
would nuke listeners we don't own).  Counters snapshot on ``__enter__`` and
report deltas, so nesting and interleaving are safe.

Donation side: XLA silently ignores ``donate_argnums`` on platforms without
buffer aliasing (CPU warns and copies), so "the windowed grower donates its
state" is only true where donation is supported.  :func:`donation_consumed`
reports whether a donated input was actually invalidated, and
:func:`assert_donation_consumed` asserts it on platforms that support
donation while degrading to a no-op where XLA ignores it — tests stay green
on the CPU tier-1 mesh and bite on device.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional

import jax
import numpy as np

from ..obs import metrics as _obs
from . import locktrace as _lt

COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"

_lock = _lt.lock("sanitizer.counts")
_counts = {"compiles": 0, "traces": 0, "dispatches": 0, "host_syncs": 0,
           "async_resolves": 0}
_installed = False


def _obs_collect() -> dict:
    """Snapshot-time bridge into the metrics registry (docs/OBSERVABILITY.md):
    this module stays the single authoritative ledger — counting here twice
    per dispatch would tax the hot path for nothing — and every metrics
    snapshot reads it once through this collector.  Process-cumulative."""
    with _lock:
        c = dict(_counts)
    return {"counters": {
        "device_compiles_total": c["compiles"],
        "device_traces_total": c["traces"],
        "device_dispatches_total": c["dispatches"],
        "device_host_syncs_total": c["host_syncs"],
        "device_async_resolves_total": c["async_resolves"],
    }}


_obs.register_collector("sanitizer", _obs_collect)


def _listener(event: str, duration: float, **_kw) -> None:  # noqa: ARG001
    if event == COMPILE_EVENT:
        with _lock:
            _counts["compiles"] += 1
    elif event == TRACE_EVENT:
        with _lock:
            _counts["traces"] += 1


def _install() -> None:
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    jax.monitoring.register_event_duration_secs_listener(_listener)


def compile_totals() -> dict:
    """Cumulative (process-lifetime) compile/trace counts since install."""
    _install()
    with _lock:
        return dict(_counts)


class RetraceError(AssertionError):
    """A jit compiled/retraced more than the test's contract allows."""


class CompileCounter:
    """Context manager counting XLA backend compiles and jaxpr traces in the
    enclosed block.

    >>> with CompileCounter() as c:
    ...     train_some_rounds()
    >>> assert c.compiles == 0  # everything was warm

    ``compiles`` counts backend (HLO -> executable) compiles: the expensive
    event, and the one "exactly one compile per (shape, dtype) config" pins.
    ``traces`` counts jaxpr traces: cheaper, but a per-round retrace that
    hits the persistent compile cache still shows up here.
    """

    def __init__(self) -> None:
        self._c0: Optional[int] = None
        self._t0: Optional[int] = None

    def __enter__(self) -> "CompileCounter":
        _install()
        with _lock:
            self._c0 = _counts["compiles"]
            self._t0 = _counts["traces"]
        return self

    def __exit__(self, *exc) -> None:
        return None

    @property
    def compiles(self) -> int:
        with _lock:
            return _counts["compiles"] - self._c0

    @property
    def traces(self) -> int:
        with _lock:
            return _counts["traces"] - self._t0

    def assert_compiles(self, expected: int, what: str = "block") -> None:
        got = self.compiles
        if got != expected:
            raise RetraceError(
                f"{what}: expected exactly {expected} backend compile(s), "
                f"observed {got} (traces: {self.traces}) — a static arg or "
                "shape is varying per call; see docs/ANALYSIS.md")

    def assert_no_recompile(self, what: str = "block") -> None:
        """The steady-state contract: zero compiles AND zero traces —
        every dispatch in the block hit a warm jit cache."""
        got_c, got_t = self.compiles, self.traces
        if got_c or got_t:
            raise RetraceError(
                f"{what}: expected a warm cache but observed {got_c} "
                f"compile(s) / {got_t} trace(s) — something retraces per "
                "call (varying static arg, new closure identity, or shape "
                "drift); see docs/ANALYSIS.md")


def expect_compiles(expected: int, what: str = "block") -> "_ExpectCompiles":
    """``with expect_compiles(1): ...`` — raises RetraceError on mismatch."""
    return _ExpectCompiles(expected, what)


class _ExpectCompiles(CompileCounter):
    def __init__(self, expected: int, what: str) -> None:
        super().__init__()
        self._expected = expected
        self._what = what

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.assert_compiles(self._expected, self._what)
        return None


# ---------------------------------------------------------------------------
# dispatch / host-sync accounting
# ---------------------------------------------------------------------------

def record_dispatch(n: int = 1) -> None:
    """Count a device dispatch issued by a host driver loop.  Call sites
    are the loop's jitted calls (one call == one XLA execution enqueued
    through the tunnel, ~1-1.5 ms each; docs/NEXT.md round-3 note).

    Honest scope: unlike compiles/traces (measured via jax.monitoring),
    dispatch counting is INSTRUMENTATION-BASED — jax emits no monitoring
    event on warm executions (verified on this toolchain), so an
    uninstrumented second dispatch in a loop is invisible to the runtime
    budget.  The structural guard for that class is static: jaxlint R6
    flags consecutive donated dispatches in round loops, and the sync
    half of the budget (``sync_pull`` vs ``async_pull_*``) covers the
    expensive regression (~45 ms blocking pulls) by routing EVERY host
    read in the drivers through this module."""
    with _lock:
        _counts["dispatches"] += n


def sync_pull(x):
    """BLOCKING host pull of a device value: the caller stalls until the
    device queue drains to this value (~45 ms through the tunnel when the
    pipeline is deep).  Returns the numpy value.  Every counted call in a
    steady-state round loop is a round-trip the loop failed to pipeline —
    the class :meth:`DispatchCounter.assert_round_budget` pins to zero."""
    with _lock:
        _counts["host_syncs"] += 1
    return np.asarray(x)


def async_pull_start(x) -> None:
    """Begin a device->host copy WITHOUT waiting (pipelined read).  Pair
    with :func:`async_pull_result` at least one dispatch later: by then
    the producing computation has retired behind newer queued work, so
    resolving the copy does not stall the device pipeline."""
    getattr(x, "copy_to_host_async", lambda: None)()


def async_pull_result(x):
    """Resolve a read started with :func:`async_pull_start`.  Counted
    separately from blocking syncs: the host may wait here, but the
    device queue keeps executing the already-dispatched rounds, so
    device utilization is unaffected (the property the windowed round
    protocol is built on)."""
    with _lock:
        _counts["async_resolves"] += 1
    return np.asarray(x)


class BudgetError(AssertionError):
    """A host round loop exceeded its dispatch/sync budget."""


class DispatchCounter(CompileCounter):
    """Context manager counting dispatches and host pulls (plus compiles/
    traces, inherited) in the enclosed block.

    >>> with DispatchCounter() as d:
    ...     grow_tree_windowed(...)
    >>> d.assert_round_budget(rounds, what="windowed growth")

    Per-rank semantics under SPMD (docs/DISTRIBUTED.md "Sharded fused
    rounds"): the ledger is per host PROCESS.  Single-controller, the
    host's one dispatch of a shard_mapped round IS every rank's dispatch
    — so "1 dispatch / 0 blocking syncs per round" counted here is the
    per-rank budget, and the in-dispatch collectives (psum/psum_scatter)
    add neither dispatches nor host syncs by construction.  In
    multi-controller runs each process carries its own ledger, pinning
    its own rank's budget independently.
    """

    def __enter__(self) -> "DispatchCounter":
        super().__enter__()
        with _lock:
            self._d0 = _counts["dispatches"]
            self._h0 = _counts["host_syncs"]
            self._a0 = _counts["async_resolves"]
        return self

    @property
    def dispatches(self) -> int:
        with _lock:
            return _counts["dispatches"] - self._d0

    @property
    def host_syncs(self) -> int:
        with _lock:
            return _counts["host_syncs"] - self._h0

    @property
    def async_resolves(self) -> int:
        with _lock:
            return _counts["async_resolves"] - self._a0

    def assert_round_budget(self, rounds: int, *,
                            dispatches_per_round: int = 1,
                            syncs_per_round: int = 0,
                            what: str = "round loop") -> None:
        """The steady-state contract of a fused round loop: exactly
        ``dispatches_per_round`` dispatches and ``syncs_per_round``
        blocking pulls per round across the block."""
        got_d, got_s = self.dispatches, self.host_syncs
        want_d = rounds * dispatches_per_round
        want_s = rounds * syncs_per_round
        if got_d != want_d or got_s != want_s:
            raise BudgetError(
                f"{what}: {rounds} round(s) budgeted "
                f"{want_d} dispatch(es) + {want_s} blocking sync(s), "
                f"observed {got_d} + {got_s} "
                f"(async resolves: {self.async_resolves}) — a phase was "
                "dispatched separately or a host pull crept into the loop; "
                "see docs/ANALYSIS.md (R6)")


def assert_ledger_agreement(stats: dict, *, collectives_per_round: int,
                            what: str = "sharded fused rounds") -> dict:
    """Static-auditor <-> runtime-ledger cross-check (docs/ANALYSIS.md
    "Jaxpr audit layer").

    The jaxpr auditor (analysis/jaxpr_audit.py J1) counts the collectives
    INSIDE the traced round executable; this check confirms the runtime
    ledger agrees they all rode the single donated dispatch: a driver
    ``stats`` dict (the windowed grower's) must show exactly ONE dispatch
    and ZERO blocking host syncs per round.  If a collective had leaked
    into the host loop (R13's runtime twin — a second dispatch or an
    eager collective), the dispatch count would exceed the round count
    and the two ledgers would disagree.  Returns the agreement summary
    embedded in audit verdicts; raises :class:`BudgetError` on mismatch.
    """
    rounds = int(stats.get("rounds", 0))
    dispatches = int(stats.get("dispatches", -1))
    syncs = int(stats.get("host_syncs", -1))
    if rounds <= 0 or dispatches != rounds or syncs != 0:
        raise BudgetError(
            f"{what}: runtime ledger ({rounds} rounds, {dispatches} "
            f"dispatches, {syncs} blocking syncs) cannot carry the "
            f"audited {collectives_per_round} in-dispatch collectives "
            "per round — a collective or a second dispatch leaked into "
            "the host loop; see docs/ANALYSIS.md (J1/R13)")
    return {"rounds": rounds, "dispatches": dispatches,
            "host_syncs": syncs,
            "collectives_per_round": collectives_per_round,
            "in_dispatch_collectives": rounds * collectives_per_round}


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------

def donation_supported() -> bool:
    """Whether the default backend honors donate_argnums (CPU ignores it)."""
    return jax.default_backend() in ("tpu", "gpu")


def donation_consumed(*arrays) -> bool:
    """True when every given donated INPUT buffer was actually invalidated
    by the call it was donated to (``Array.is_deleted``)."""
    return all(getattr(a, "is_deleted", lambda: False)() for a in arrays)


def assert_donation_consumed(arrays: Iterable, what: str = "donated state"
                             ) -> None:
    """Assert donated inputs were consumed — i.e. the donation actually
    took (the donated jit aliased the buffers) AND the caller cannot be
    holding a live reference it might read after the call.  No-op on
    platforms where XLA ignores donation."""
    if not donation_supported():
        return
    arrays = list(arrays)
    if not donation_consumed(*arrays):
        alive = sum(1 for a in arrays
                    if not getattr(a, "is_deleted", lambda: False)())
        raise AssertionError(
            f"{what}: {alive}/{len(arrays)} donated buffer(s) still alive "
            "after the call — donation was dropped (aliasing rejected) or "
            "the state is not threaded linearly (jaxlint R3)")
