"""Runtime lock tracing: the dynamic half of the concurrency layer.

The static lint (``lightgbm_tpu.analysis.locks``, rules L1-L5) proves what
it can see lexically; this module watches what actually happens.  Every
named lock minted through the :func:`lock` / :func:`rlock` /
:func:`condition` factories is a thin wrapper around the matching
``threading`` primitive that, when tracing is enabled, additionally

* keeps a **per-thread held set** (thread-local; no extra locking on the
  hot path beyond the wrapped primitive itself),
* maintains a process-wide **witness graph** of observed acquisition
  orders keyed by lock *name* — the first time the process acquires
  ``B`` while holding ``A`` the edge ``A -> B`` is recorded together
  with its call site; a later acquire that would close a cycle raises
  :class:`LockOrderError` (strict mode) naming **both** sites, or counts
  it (record mode),
* converts every blocking acquire into a **timeout acquire**
  (``LGBMTPU_LOCK_TIMEOUT_S``, default 60s) so a true deadlock surfaces
  as a typed :class:`LockTimeoutError` instead of a hung process,
* exports ``lock_wait_ms{lock=<name>}`` / ``lock_held_ms{lock=<name>}``
  reservoirs and the ``lock_order_violations_total`` /
  ``lock_deadlock_timeouts_total`` counters through the obs registry.

Same-name, different-instance nesting (e.g. two ``GBDT`` pack locks held
by one rollover thread) records no self-edge: the witness graph is a
*name*-level order discipline, and a name never orders against itself.

Layering: :mod:`lightgbm_tpu.obs` is stdlib-only and must stay importable
without this package, so obs-internal locks remain plain ``threading``
locks (covered by the static layer only) and this module imports
``obs.metrics`` lazily, inside functions, behind a thread-local mute
guard.  Enable for a whole run with ``LGBMTPU_LOCKTRACE=1`` or from code
via :func:`enable`; the tier-1 suite turns it on (strict) in conftest.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "LockOrderError", "LockTimeoutError", "TracedCondition", "TracedLock",
    "condition", "enable", "enabled", "lock", "rlock", "reset", "stats",
    "timeout_s",
]


class LockOrderError(RuntimeError):
    """Acquiring this lock would close a cycle in the witness graph."""


class LockTimeoutError(RuntimeError):
    """A traced acquire exceeded the deadlock timeout (or a thread
    re-acquired a non-reentrant traced lock it already holds)."""


def _env_flag(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("", "0", "false", "off", "no")


_ENABLED = _env_flag("LGBMTPU_LOCKTRACE", False)
_STRICT = _env_flag("LGBMTPU_LOCKTRACE_STRICT", True)
_TIMEOUT_S = float(os.environ.get("LGBMTPU_LOCK_TIMEOUT_S", "60"))

# Witness graph + counters.  _graph_lock is a leaf: nothing (traced or
# not) is ever acquired while holding it, and no blocking call runs
# under it — the obs export happens after release, behind the mute TLS.
_graph_lock = threading.Lock()
_edges: Dict[Tuple[str, str], Tuple[str, int]] = {}  # (held, acq) -> site
_order_violations = 0
_deadlock_timeouts = 0

_tls = threading.local()  # .held: List[TracedLock], .mute: bool


def _held_stack() -> List["TracedLock"]:
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
    return st


def _call_site() -> Tuple[str, int]:
    """First frame outside this module — the acquire's real call site."""
    f = sys._getframe(1)
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:  # pragma: no cover — only if called at module top level
        return ("<unknown>", 0)
    return (f.f_code.co_filename, f.f_lineno)


def _reaches(src: str, dst: str) -> Optional[List[Tuple[str, str]]]:
    """DFS path src -> dst over the witness edges (caller holds
    _graph_lock); returns the edge list of one path, else None."""
    stack: List[Tuple[str, List[Tuple[str, str]]]] = [(src, [])]
    seen = {src}
    adj: Dict[str, List[str]] = {}
    for (a, b) in _edges:
        adj.setdefault(a, []).append(b)
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in adj.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [(node, nxt)]))
    return None


def _record(fn) -> None:
    """Run an obs-recording thunk behind the reentrancy mute guard."""
    if getattr(_tls, "mute", False):
        return
    _tls.mute = True
    try:
        fn()
    except Exception:
        pass  # observability must never take down the locked path
    finally:
        _tls.mute = False


def _obs_counter_inc(name: str) -> None:
    def thunk():
        from ..obs import metrics as _m
        _m.counter(name).inc()
    _record(thunk)


def _obs_observe_ms(family: str, lock_name: str, ms: float) -> None:
    def thunk():
        from ..obs import metrics as _m
        _m.histogram(_m.labeled(family, lock=lock_name)).observe(ms)
    _record(thunk)


class TracedLock:
    """Named wrapper over ``threading.Lock``/``RLock`` with witness-graph
    order checking, timeout acquire, and wait/held timing.

    When tracing is disabled the wrapper is a plain pass-through (one
    attribute hop per acquire/release) so factory call sites never need
    to branch on the mode themselves.
    """

    __slots__ = ("name", "reentrant", "_inner", "_depth", "_acquired_at")

    def __init__(self, name: str, reentrant: bool = False) -> None:
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._depth: Dict[int, int] = {}       # thread ident -> hold depth
        self._acquired_at: Dict[int, float] = {}  # ident -> monotonic ts

    # -- order discipline -------------------------------------------------

    def _check_order(self) -> None:
        """Witness-graph update for acquiring self while holding the
        thread's current stack; raises LockOrderError on a cycle."""
        global _order_violations
        held = _held_stack()
        if not held:
            return
        me = self.name
        site = _call_site()
        violation: Optional[str] = None
        with _graph_lock:
            for h in held:
                if h.name == me:
                    continue  # same name never orders against itself
                edge = (h.name, me)
                if edge in _edges:
                    continue
                back = _reaches(me, h.name)
                if back is not None:
                    first_a, first_b = back[0]
                    f_file, f_line = _edges[(first_a, first_b)]
                    _order_violations += 1
                    violation = (
                        f"lock-order inversion: acquiring '{me}' while "
                        f"holding '{h.name}' at {site[0]}:{site[1]}, but "
                        f"the witness graph orders '{first_a}' before "
                        f"'{first_b}' (first seen at {f_file}:{f_line})"
                    )
                    break
                _edges[edge] = site
        if violation is not None:
            _obs_counter_inc("lock_order_violations_total")
            if _STRICT:
                raise LockOrderError(violation)

    # -- lock protocol ----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _ENABLED:
            return self._inner.acquire(blocking, timeout)
        global _deadlock_timeouts
        ident = threading.get_ident()
        depth = self._depth.get(ident, 0)
        if depth and not self.reentrant:
            with _graph_lock:
                _deadlock_timeouts += 1
            _obs_counter_inc("lock_deadlock_timeouts_total")
            raise LockTimeoutError(
                f"self-deadlock: thread re-acquired non-reentrant lock "
                f"'{self.name}' it already holds "
                f"(at {':'.join(map(str, _call_site()))})"
            )
        if depth == 0:
            self._check_order()
        t0 = time.monotonic()
        if not blocking:
            ok = self._inner.acquire(False)
        else:
            eff = timeout if timeout is not None and timeout >= 0 else _TIMEOUT_S
            ok = self._inner.acquire(True, eff)
            if not ok and (timeout is None or timeout < 0):
                with _graph_lock:
                    _deadlock_timeouts += 1
                _obs_counter_inc("lock_deadlock_timeouts_total")
                raise LockTimeoutError(
                    f"deadlock suspected: lock '{self.name}' not acquired "
                    f"within {_TIMEOUT_S:.1f}s "
                    f"(at {':'.join(map(str, _call_site()))})"
                )
        if ok:
            if depth == 0:
                self._acquired_at[ident] = time.monotonic()
                _held_stack().append(self)
                _obs_observe_ms(
                    "lock_wait_ms", self.name,
                    (time.monotonic() - t0) * 1000.0)
            self._depth[ident] = depth + 1
        return ok

    def release(self) -> None:
        if not _ENABLED:
            self._inner.release()
            return
        ident = threading.get_ident()
        depth = self._depth.get(ident, 0)
        if depth <= 0:
            # never acquired through the traced path (e.g. tracing was
            # flipped on mid-hold) — fall through to the primitive
            self._inner.release()
            return
        if depth == 1:
            del self._depth[ident]
            t0 = self._acquired_at.pop(ident, None)
            st = _held_stack()
            if self in st:
                st.remove(self)
            if t0 is not None:
                _obs_observe_ms(
                    "lock_held_ms", self.name,
                    (time.monotonic() - t0) * 1000.0)
        else:
            self._depth[ident] = depth - 1
        self._inner.release()

    def locked(self) -> bool:
        if self.reentrant:
            return bool(self._depth)
        return self._inner.locked()

    def _is_owned(self) -> bool:
        """threading.Condition hook: does the current thread hold us?"""
        if not _ENABLED:
            # best-effort probe, mirroring Condition's default fallback
            if self._inner.acquire(False):
                self._inner.release()
                return False
            return True
        return self._depth.get(threading.get_ident(), 0) > 0

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        kind = "rlock" if self.reentrant else "lock"
        return f"<TracedLock {kind} '{self.name}' depth={dict(self._depth)}>"


class TracedCondition(threading.Condition):
    """``threading.Condition`` over a named non-reentrant TracedLock.

    Condition's own wait/notify machinery calls ``self._lock.acquire`` /
    ``release`` directly, so the witness bookkeeping stays consistent
    across ``wait()``'s release/re-acquire; ``_is_owned`` comes from the
    traced lock's thread-local depth instead of the probe fallback.
    """

    def __init__(self, name: str) -> None:
        self.name = name
        super().__init__(TracedLock(name, reentrant=False))


def lock(name: str) -> TracedLock:
    """A named, traced ``threading.Lock``."""
    return TracedLock(name, reentrant=False)


def rlock(name: str) -> TracedLock:
    """A named, traced ``threading.RLock``."""
    return TracedLock(name, reentrant=True)


def condition(name: str) -> TracedCondition:
    """A named ``threading.Condition`` over a traced lock."""
    return TracedCondition(name)


def enable(on: bool = True, strict: bool = True) -> None:
    """Flip runtime tracing for the whole process.

    ``strict=True`` raises :class:`LockOrderError` on a witnessed
    inversion; ``strict=False`` only counts it (record mode).  Locks
    minted before the flip participate from their next acquire on.
    """
    global _ENABLED, _STRICT
    _ENABLED = bool(on)
    _STRICT = bool(strict)


def enabled() -> bool:
    return _ENABLED


def timeout_s() -> float:
    return _TIMEOUT_S


def set_timeout_s(s: float) -> None:
    """Deadlock-suspicion bound for blocking acquires (tests)."""
    global _TIMEOUT_S
    _TIMEOUT_S = float(s)


def reset() -> None:
    """Clear the witness graph and the violation counters (tests).

    Obs-side counters are owned by the registry — reset those with
    ``lightgbm_tpu.obs.reset()``."""
    global _order_violations, _deadlock_timeouts
    with _graph_lock:
        _edges.clear()
        _order_violations = 0
        _deadlock_timeouts = 0


def stats() -> Dict[str, int]:
    """Internal tallies, independent of the obs registry lifecycle."""
    with _graph_lock:
        return {
            "witness_edges": len(_edges),
            "order_violations": _order_violations,
            "deadlock_timeouts": _deadlock_timeouts,
        }


def witness_edges() -> Dict[Tuple[str, str], Tuple[str, int]]:
    """Snapshot of the witness graph: (held, acquired) -> first site."""
    with _graph_lock:
        return dict(_edges)
