"""Host-side utilities: logging, profiling, runtime sanitizers."""

from .sanitizer import (CompileCounter, RetraceError, assert_donation_consumed,
                        compile_totals, donation_consumed, donation_supported,
                        expect_compiles)

__all__ = [
    "CompileCounter", "RetraceError", "assert_donation_consumed",
    "compile_totals", "donation_consumed", "donation_supported",
    "expect_compiles",
]
