"""Durable checkpoints: atomic model writes + integrity trailers + torn-
snapshot fallback (docs/ROBUSTNESS.md).

The reference's entire fault model is ``snapshot_freq``: GBDT::Train
writes ``<output_model>.snapshot_iter_<n>`` every freq iterations and a
restart loads it via ``input_model``.  A crash MID-WRITE, however, leaves
a torn file that a restart happily parses into a half-model — the exact
silent-corruption class a recovery story must exclude.  Three properties
fix it:

* **Atomicity** — every model file is written to a same-directory temp
  file, fsync'd, and ``os.replace``d into place.  A crash at any point
  leaves either the old file or the new file, never a hybrid; stray
  ``*.tmp.*`` files are garbage, not checkpoints.
* **Integrity trailer** — snapshots carry a final comment line
  ``# lgbm-tpu-checkpoint v1 sha256=<hex> bytes=<n>`` over the payload.
  The model-text parser never sees it (loads strip it), and a resume can
  distinguish "valid snapshot" from "torn/bit-rotted file" instead of
  trusting mtime.
* **Fallback scan** — :func:`latest_valid_snapshot` walks the snapshot
  family of an output model, newest first, and returns the first one
  whose trailer verifies; engine.train resumes from it when the
  requested snapshot fails verification.

Kept import-light (stdlib + utils only): basic.py and engine.py both use
it, and the launcher's thin worker processes must not pay a jax import
to write a model atomically.
"""

from __future__ import annotations

import hashlib
import os
import re
import tempfile
from typing import List, Optional, Tuple

from . import faults

TRAILER_VERSION = "v1"
_TRAILER_RE = re.compile(
    r"^# lgbm-tpu-checkpoint (?P<ver>v\d+) sha256=(?P<digest>[0-9a-f]{64}) "
    r"bytes=(?P<nbytes>\d+)\s*$")
_SNAPSHOT_RE = re.compile(r"^(?P<prefix>.*)\.snapshot_iter_(?P<it>\d+)$")


def _digest(payload: str) -> str:
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()


def add_trailer(payload: str) -> str:
    """Append the integrity trailer line to a model text."""
    if not payload.endswith("\n"):
        payload += "\n"
    return (f"{payload}# lgbm-tpu-checkpoint {TRAILER_VERSION} "
            f"sha256={_digest(payload)} bytes={len(payload.encode('utf-8'))}\n")


def verify_text(text: str) -> Tuple[str, Optional[bool]]:
    """Split a model text into (payload, verdict).

    verdict is True (trailer present and verifies), False (trailer
    present but digest/length mismatch — a torn or corrupted file), or
    None (no trailer: a plain model file, nothing to verify)."""
    lines = text.splitlines(keepends=True)
    for i in range(len(lines) - 1, -1, -1):
        if lines[i].strip():
            m = _TRAILER_RE.match(lines[i].strip())
            if m is None:
                return text, None
            payload = "".join(lines[:i])
            ok = (m.group("ver") == TRAILER_VERSION
                  and len(payload.encode("utf-8")) == int(m.group("nbytes"))
                  and _digest(payload) == m.group("digest"))
            return payload, ok
    return text, None


def atomic_write_text(path: str, text: str,
                      fault_round: Optional[int] = None) -> None:
    """Write ``text`` to ``path`` atomically (same-dir temp + fsync +
    ``os.replace``).  ``fault_round`` arms the ``snapshot_write``
    injection site mid-write (utils/faults.py): the crash lands after a
    partial payload is flushed to the TEMP file, proving no torn file can
    reach the final path."""
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".tmp.", dir=d)
    try:
        # mkstemp creates 0600; restore umask-based permissions so the
        # final file is readable exactly as a plain open()-write would be
        # (shared model dirs, serving processes under another uid)
        umask = os.umask(0)
        os.umask(umask)
        os.fchmod(fd, 0o666 & ~umask)
        # utf-8 everywhere: the trailer digest and the verify readers
        # hash/decode utf-8 — the write must not follow the locale
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            if fault_round is not None and faults.armed("snapshot_write"):
                # injection scaffolding only when armed: the extra
                # flush+fsync of the split write must not tax every
                # production snapshot
                half = text[: len(text) // 2]
                fh.write(half)
                fh.flush()
                os.fsync(fh.fileno())
                faults.maybe_crash("snapshot_write", fault_round)
                fh.write(text[len(half):])
            else:
                fh.write(text)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    # telemetry AFTER the replace: only durable writes count (lazy import —
    # thin launcher workers write models without extra import cost)
    from ..obs import metrics as _obs

    _obs.counter("checkpoint_writes_total").inc()


def save_snapshot(path: str, model_text: str, iteration: int) -> None:
    """Atomic, trailer-stamped snapshot write (engine.py snapshot_freq)."""
    atomic_write_text(path, add_trailer(model_text), fault_round=iteration)
    from ..obs import metrics as _obs

    _obs.counter("checkpoint_snapshots_total").inc()
    _obs.event("checkpoint_snapshot", path=os.fspath(path),
               iteration=iteration)


def verify_file(path: str) -> Optional[bool]:
    """Trailer verdict for a file on disk (see :func:`verify_text`).
    Unreadable files count as torn (False), and so does a SNAPSHOT-named
    file with no trailer at all — snapshots are always written with one,
    so truncation that ate the trailer line must not read as 'legacy
    file, nothing to verify'."""
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except (OSError, UnicodeDecodeError):
        ok = False
    else:
        ok = verify_text(text)[1]
        if ok is None and is_snapshot_path(path):
            ok = False
    if ok is False:
        from ..obs import metrics as _obs

        _obs.counter("checkpoint_torn_total").inc()
        _obs.event("checkpoint_torn", path=os.fspath(path))
    return ok


def snapshot_iteration(path: str) -> Optional[int]:
    """The <k> of a ``*.snapshot_iter_<k>`` path, None for other paths."""
    m = _SNAPSHOT_RE.match(os.fspath(path))
    return int(m.group("it")) if m else None


def is_snapshot_path(path: str) -> bool:
    """True for ``*.snapshot_iter_<k>`` paths.  Snapshots are ALWAYS
    written with a trailer, so a snapshot-named file without a valid one
    is torn by definition — truncation that chops the trailer off must
    not demote a snapshot to an unverifiable 'legacy' file."""
    return _SNAPSHOT_RE.match(os.fspath(path)) is not None


def read_and_verify(path: str) -> Tuple[str, Optional[bool]]:
    """(payload, raw trailer verdict) for a file on disk — unlike
    :func:`verify_file` this reports the TEXT verdict (None = no trailer)
    so callers can distinguish a pre-trailer-era file from a torn one.
    An undecodable file reports ("", False): corrupted, not a crash."""
    try:
        with open(path, encoding="utf-8") as fh:
            return verify_text(fh.read())
    except UnicodeDecodeError:
        return "", False


def snapshot_family(path: str) -> List[Tuple[int, str]]:
    """All ``<prefix>.snapshot_iter_<k>`` siblings of ``path`` (itself a
    snapshot path or the bare output-model prefix), sorted newest first."""
    m = _SNAPSHOT_RE.match(os.fspath(path))
    prefix = m.group("prefix") if m else os.fspath(path)
    base_dir = os.path.dirname(os.path.abspath(prefix)) or "."
    base_name = os.path.basename(prefix)
    out = []
    try:
        entries = os.listdir(base_dir)
    except OSError:
        return []
    for name in entries:
        sm = _SNAPSHOT_RE.match(name)
        if sm is not None and sm.group("prefix") == base_name:
            out.append((int(sm.group("it")), os.path.join(base_dir, name)))
    out.sort(reverse=True)
    return out


def latest_valid_snapshot(path: str,
                          below_iter: Optional[int] = None
                          ) -> Optional[Tuple[int, str]]:
    """Newest snapshot in ``path``'s family whose trailer VERIFIES
    (trailerless files are skipped — they cannot be vouched for).
    ``below_iter`` restricts the scan to strictly older snapshots (the
    fallback case: the iter-k snapshot is torn, look before k)."""
    for it, snap in snapshot_family(path):
        if below_iter is not None and it >= below_iter:
            continue
        if verify_file(snap) is True:
            return it, snap
    return None
