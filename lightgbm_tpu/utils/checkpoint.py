"""Durable checkpoints: atomic model writes + integrity trailers + torn-
snapshot fallback (docs/ROBUSTNESS.md).

The reference's entire fault model is ``snapshot_freq``: GBDT::Train
writes ``<output_model>.snapshot_iter_<n>`` every freq iterations and a
restart loads it via ``input_model``.  A crash MID-WRITE, however, leaves
a torn file that a restart happily parses into a half-model — the exact
silent-corruption class a recovery story must exclude.  Three properties
fix it:

* **Atomicity** — every model file is written to a same-directory temp
  file, fsync'd, and ``os.replace``d into place.  A crash at any point
  leaves either the old file or the new file, never a hybrid; stray
  ``*.tmp.*`` files are garbage, not checkpoints.
* **Integrity trailer** — snapshots carry a final comment line
  ``# lgbm-tpu-checkpoint v1 sha256=<hex> bytes=<n>`` over the payload.
  The model-text parser never sees it (loads strip it), and a resume can
  distinguish "valid snapshot" from "torn/bit-rotted file" instead of
  trusting mtime.
* **Fallback scan** — :func:`latest_valid_snapshot` walks the snapshot
  family of an output model, newest first, and returns the first one
  whose trailer verifies; engine.train resumes from it when the
  requested snapshot fails verification.

Kept import-light (stdlib + utils only): basic.py and engine.py both use
it, and the launcher's thin worker processes must not pay a jax import
to write a model atomically.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import tempfile
import time
from typing import Dict, List, Optional, Tuple

from . import faults

TRAILER_VERSION = "v1"
_TRAILER_RE = re.compile(
    r"^# lgbm-tpu-checkpoint (?P<ver>v\d+) sha256=(?P<digest>[0-9a-f]{64}) "
    r"bytes=(?P<nbytes>\d+)\s*$")
_SNAPSHOT_RE = re.compile(r"^(?P<prefix>.*)\.snapshot_iter_(?P<it>\d+)$")


def _digest(payload: str) -> str:
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()


def add_trailer(payload: str) -> str:
    """Append the integrity trailer line to a model text."""
    if not payload.endswith("\n"):
        payload += "\n"
    return (f"{payload}# lgbm-tpu-checkpoint {TRAILER_VERSION} "
            f"sha256={_digest(payload)} bytes={len(payload.encode('utf-8'))}\n")


def verify_text(text: str) -> Tuple[str, Optional[bool]]:
    """Split a model text into (payload, verdict).

    verdict is True (trailer present and verifies), False (trailer
    present but digest/length mismatch — a torn or corrupted file), or
    None (no trailer: a plain model file, nothing to verify)."""
    lines = text.splitlines(keepends=True)
    for i in range(len(lines) - 1, -1, -1):
        if lines[i].strip():
            m = _TRAILER_RE.match(lines[i].strip())
            if m is None:
                return text, None
            payload = "".join(lines[:i])
            ok = (m.group("ver") == TRAILER_VERSION
                  and len(payload.encode("utf-8")) == int(m.group("nbytes"))
                  and _digest(payload) == m.group("digest"))
            return payload, ok
    return text, None


def atomic_write_text(path: str, text: str,
                      fault_round: Optional[int] = None) -> None:
    """Write ``text`` to ``path`` atomically (same-dir temp + fsync +
    ``os.replace``).  ``fault_round`` arms the ``snapshot_write``
    injection site mid-write (utils/faults.py): the crash lands after a
    partial payload is flushed to the TEMP file, proving no torn file can
    reach the final path."""
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".tmp.", dir=d)
    try:
        # mkstemp creates 0600; restore umask-based permissions so the
        # final file is readable exactly as a plain open()-write would be
        # (shared model dirs, serving processes under another uid)
        umask = os.umask(0)
        os.umask(umask)
        os.fchmod(fd, 0o666 & ~umask)
        # utf-8 everywhere: the trailer digest and the verify readers
        # hash/decode utf-8 — the write must not follow the locale
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            if fault_round is not None and faults.armed("snapshot_write"):
                # injection scaffolding only when armed: the extra
                # flush+fsync of the split write must not tax every
                # production snapshot
                half = text[: len(text) // 2]
                fh.write(half)
                fh.flush()
                os.fsync(fh.fileno())
                faults.maybe_crash("snapshot_write", fault_round)
                fh.write(text[len(half):])
            else:
                fh.write(text)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    # telemetry AFTER the replace: only durable writes count (lazy import —
    # thin launcher workers write models without extra import cost)
    from ..obs import metrics as _obs

    _obs.counter("checkpoint_writes_total").inc()


def save_snapshot(path: str, model_text: str, iteration: int) -> None:
    """Atomic, trailer-stamped snapshot write (engine.py snapshot_freq)."""
    atomic_write_text(path, add_trailer(model_text), fault_round=iteration)
    from ..obs import metrics as _obs

    _obs.counter("checkpoint_snapshots_total").inc()
    _obs.event("checkpoint_snapshot", path=os.fspath(path),
               iteration=iteration)


def verify_file(path: str) -> Optional[bool]:
    """Trailer verdict for a file on disk (see :func:`verify_text`).
    Unreadable files count as torn (False), and so does a SNAPSHOT-named
    file with no trailer at all — snapshots are always written with one,
    so truncation that ate the trailer line must not read as 'legacy
    file, nothing to verify'."""
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except (OSError, UnicodeDecodeError):
        ok = False
    else:
        ok = verify_text(text)[1]
        if ok is None and is_snapshot_path(path):
            ok = False
    if ok is False:
        from ..obs import metrics as _obs

        _obs.counter("checkpoint_torn_total").inc()
        _obs.event("checkpoint_torn", path=os.fspath(path))
    return ok


def snapshot_iteration(path: str) -> Optional[int]:
    """The <k> of a ``*.snapshot_iter_<k>`` path, None for other paths."""
    m = _SNAPSHOT_RE.match(os.fspath(path))
    return int(m.group("it")) if m else None


def is_snapshot_path(path: str) -> bool:
    """True for ``*.snapshot_iter_<k>`` paths.  Snapshots are ALWAYS
    written with a trailer, so a snapshot-named file without a valid one
    is torn by definition — truncation that chops the trailer off must
    not demote a snapshot to an unverifiable 'legacy' file."""
    return _SNAPSHOT_RE.match(os.fspath(path)) is not None


def read_and_verify(path: str) -> Tuple[str, Optional[bool]]:
    """(payload, raw trailer verdict) for a file on disk — unlike
    :func:`verify_file` this reports the TEXT verdict (None = no trailer)
    so callers can distinguish a pre-trailer-era file from a torn one.
    An undecodable file reports ("", False): corrupted, not a crash."""
    try:
        with open(path, encoding="utf-8") as fh:
            return verify_text(fh.read())
    except UnicodeDecodeError:
        return "", False


def snapshot_family(path: str) -> List[Tuple[int, str]]:
    """All ``<prefix>.snapshot_iter_<k>`` siblings of ``path`` (itself a
    snapshot path or the bare output-model prefix), sorted newest first."""
    m = _SNAPSHOT_RE.match(os.fspath(path))
    prefix = m.group("prefix") if m else os.fspath(path)
    base_dir = os.path.dirname(os.path.abspath(prefix)) or "."
    base_name = os.path.basename(prefix)
    out = []
    try:
        entries = os.listdir(base_dir)
    except OSError:
        return []
    for name in entries:
        sm = _SNAPSHOT_RE.match(name)
        if sm is not None and sm.group("prefix") == base_name:
            out.append((int(sm.group("it")), os.path.join(base_dir, name)))
    out.sort(reverse=True)
    return out


def latest_valid_snapshot(path: str,
                          below_iter: Optional[int] = None
                          ) -> Optional[Tuple[int, str]]:
    """Newest snapshot in ``path``'s family whose trailer VERIFIES
    (trailerless files are skipped — they cannot be vouched for).
    ``below_iter`` restricts the scan to strictly older snapshots (the
    fallback case: the iter-k snapshot is torn, look before k)."""
    for it, snap in snapshot_family(path):
        if below_iter is not None and it >= below_iter:
            continue
        if verify_file(snap) is True:
            return it, snap
    return None


# ---------------------------------------------------------------------------
# retention: bounded snapshot families (snapshot_keep=)
# ---------------------------------------------------------------------------

def prune_snapshots(path: str, keep: int) -> List[Tuple[int, str]]:
    """Delete the oldest snapshots in ``path``'s family beyond the newest
    ``keep`` of them — but NEVER the newest snapshot that actually
    verifies, whatever its age: retention must not be able to throw away
    the only state a resume could use (a family whose newest ``keep``
    entries are all torn keeps its last good snapshot).  ``keep <= 0``
    means keep-all (the default behavior).  Returns the pruned
    ``(iteration, path)`` pairs; each deletion is evented through obs."""
    if keep <= 0:
        return []
    family = snapshot_family(path)  # newest first
    newest_valid: Optional[str] = None
    for _, snap in family:
        if verify_file(snap) is True:
            newest_valid = snap
            break
    pruned: List[Tuple[int, str]] = []
    for it, snap in family[keep:]:
        if snap == newest_valid:
            continue
        try:
            os.unlink(snap)
        except OSError:
            continue  # already gone / unremovable: not worth failing a run
        pruned.append((it, snap))
    if pruned:
        from ..obs import metrics as _obs

        _obs.counter("checkpoint_pruned_total").inc(len(pruned))
        _obs.event("checkpoint_prune", path=os.fspath(path),
                   kept=keep, pruned=[p for _, p in pruned])
    return pruned


# ---------------------------------------------------------------------------
# fleet-consistent checkpoints (docs/ROBUSTNESS.md "Elastic fleet recovery")
#
# A fleet checkpoint for round k is three things, all in the launch dir:
#   fleet.snapshot_iter_<k>            rank 0's model snapshot (sha256
#                                      trailer via save_snapshot, raw-delta
#                                      form so resume is bitwise)
#   fleet.manifest_iter_<k>.json       the manifest (schema below), written
#                                      ATOMICALLY and only AFTER the
#                                      snapshot is durable
#   fleet.manifest_iter_<k>.ack.rank<r>  one marker per non-zero rank,
#                                      carrying that rank's own ensemble
#                                      sha256 at round k
#
# A round is *fleet-valid* — and only then resumable — when the manifest
# parses, the snapshot's trailer verifies, the snapshot payload hashes to
# the manifest's ensemble_sha256, and every rank 1..W-1 has acked with a
# MATCHING ensemble sha.  A crash anywhere in the protocol (including the
# armed ``manifest_write`` injection window between snapshot and manifest)
# leaves the previous fleet-valid round authoritative.
# ---------------------------------------------------------------------------

FLEET_SCHEMA = "lgbmtpu-fleet-ckpt-v1"
_FLEET_MANIFEST_RE = re.compile(r"^fleet\.manifest_iter_(?P<it>\d+)\.json$")


def fleet_snapshot_path(d: str, round_i: int) -> str:
    return os.path.join(d, f"fleet.snapshot_iter_{round_i}")


def fleet_manifest_path(d: str, round_i: int) -> str:
    return os.path.join(d, f"fleet.manifest_iter_{round_i}.json")


def fleet_ack_path(d: str, round_i: int, rank: int) -> str:
    return os.path.join(d, f"fleet.manifest_iter_{round_i}.ack.rank{rank}")


def ensemble_digest(model_text: str) -> str:
    """sha256 over the model text normalized exactly as the snapshot
    trailer hashes it (trailing newline ensured) — so the manifest's
    ensemble_sha256 equals the snapshot trailer's digest and cross-checks
    are byte-for-byte."""
    if not model_text.endswith("\n"):
        model_text += "\n"
    return _digest(model_text)


def write_fleet_checkpoint(d: str, model_text: str, round_i: int,
                           world_size: int,
                           shard_fingerprints: Optional[Dict[str, str]] = None,
                           keep: int = 0,
                           slices: Optional[Dict[str, int]] = None) -> str:
    """Rank 0's half of the protocol: durable snapshot FIRST, manifest
    publish SECOND (the ordering is the whole point — a manifest may never
    refer to a snapshot that might not exist).  ``shard_fingerprints``
    maps rank -> data-shard sha256 so a resumed rank can refuse to
    continue on changed data.  ``keep`` > 0 prunes old fleet rounds after
    a successful publish (never the newest valid one).  ``slices`` maps
    rank -> slice id for multi-slice fleets (docs/ROBUSTNESS.md
    "Slice-granular recovery"): it lets :func:`
    latest_slice_valid_fleet_manifest` answer which rounds a REPLACEMENT
    slice can rejoin at without the lost slice's own acks.  Returns the
    manifest path."""
    snap = fleet_snapshot_path(d, round_i)
    save_snapshot(snap, model_text, round_i)
    # torn-fleet-state injection window (utils/faults.py manifest_write):
    # the snapshot is durable but the manifest making it fleet-valid is
    # not yet — a crash here must leave the PREVIOUS round authoritative
    faults.maybe_crash("manifest_write", round_i)
    manifest = {
        "schema": FLEET_SCHEMA,
        "round": int(round_i),
        "snapshot": os.path.basename(snap),
        "ensemble_sha256": ensemble_digest(model_text),
        "world_size": int(world_size),
        "shards": {str(r): str(fp)
                   for r, fp in (shard_fingerprints or {}).items()},
        "ts": time.time(),
    }
    if slices:
        manifest["slices"] = {str(r): int(s) for r, s in slices.items()}
        manifest["num_slices"] = len(set(manifest["slices"].values()))
    atomic_write_text(fleet_manifest_path(d, round_i),
                      json.dumps(manifest, indent=1) + "\n")
    from ..obs import metrics as _obs

    _obs.counter("fleet_checkpoints_total").inc()
    _obs.event("fleet_checkpoint", round=int(round_i),
               manifest=fleet_manifest_path(d, round_i),
               world_size=int(world_size))
    if keep > 0:
        prune_fleet_checkpoints(d, keep)
    return fleet_manifest_path(d, round_i)


def confirm_fleet_checkpoint(d: str, round_i: int, rank: int,
                             model_text: Optional[str] = None) -> str:
    """A non-zero rank's half: drop the ack marker for round ``round_i``.
    With ``model_text`` the ack carries this rank's own ensemble sha256,
    so fleet validity additionally proves cross-rank state CONSISTENCY
    (an empty ack only proves liveness through the round).  Markers are
    written atomically — a torn ack must read as absent, not garbage."""
    ack = fleet_ack_path(d, round_i, rank)
    sha = ensemble_digest(model_text) if model_text is not None else ""
    atomic_write_text(ack, sha + "\n")
    return ack


def fleet_manifest_valid(manifest_path: str,
                         world_size: Optional[int] = None,
                         exclude_ranks: Tuple[int, ...] = ()
                         ) -> Optional[Dict]:
    """The fleet-validity check.  Returns the manifest dict (with
    ``snapshot`` resolved to an absolute path) when EVERY leg holds:

    * the manifest parses and carries the ``lgbmtpu-fleet-ckpt-v1`` schema
      (with a sane round and world_size);
    * ``world_size``, when given, matches the manifest's (a resume must
      not mix fleet sizes — shard fingerprints are per-rank);
    * the snapshot exists and its sha256 trailer verifies;
    * the snapshot payload hashes to the manifest's ``ensemble_sha256``;
    * every rank 1..W-1 has an ack, and every sha-carrying ack matches.

    ``exclude_ranks`` drops the ack requirement for the named ranks —
    the slice-granular recovery form (docs/ROBUSTNESS.md): a LOST
    slice's members cannot ack any more, and the round the replacement
    slice rejoins at needs only the SURVIVING ranks' confirmation.  An
    excluded rank's ack, when present, must still MATCH (a diverged ack
    proves inconsistent state whoever wrote it).

    Anything else returns None — an unconfirmed or torn round is never
    resumed into."""
    d = os.path.dirname(os.path.abspath(manifest_path))
    try:
        with open(manifest_path, encoding="utf-8") as fh:
            manifest = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(manifest, dict) or manifest.get("schema") != FLEET_SCHEMA:
        return None
    try:
        round_i = int(manifest["round"])
        w = int(manifest["world_size"])
        snap_name = str(manifest["snapshot"])
        want_sha = str(manifest["ensemble_sha256"])
    except (KeyError, TypeError, ValueError):
        return None
    if round_i < 1 or w < 1:
        return None
    if world_size is not None and w != int(world_size):
        return None
    snap = os.path.join(d, snap_name)
    payload, ok = read_and_verify(snap)
    if ok is not True or _digest(payload) != want_sha:
        return None
    excluded = {int(r) for r in exclude_ranks}
    for r in range(1, w):
        try:
            with open(fleet_ack_path(d, round_i, r),
                      encoding="utf-8") as fh:
                ack_sha = fh.read().strip()
        except OSError:
            if r in excluded:
                continue  # a lost slice's member cannot ack any more
            return None  # unconfirmed rank: not fleet-valid
        if ack_sha and ack_sha != want_sha:
            return None  # rank diverged from rank 0's ensemble
    manifest = dict(manifest)
    manifest["snapshot"] = snap
    return manifest


def latest_valid_fleet_manifest(d: str,
                                world_size: Optional[int] = None
                                ) -> Optional[Tuple[int, str, Dict]]:
    """Newest fleet-VALID round in directory ``d``: scans
    ``fleet.manifest_iter_<k>.json`` newest-first and returns
    ``(round, manifest_path, manifest)`` for the first one that passes
    :func:`fleet_manifest_valid`, else None."""
    try:
        entries = os.listdir(d)
    except OSError:
        return None
    rounds = []
    for name in entries:
        m = _FLEET_MANIFEST_RE.match(name)
        if m is not None:
            rounds.append(int(m.group("it")))
    for round_i in sorted(rounds, reverse=True):
        path = fleet_manifest_path(d, round_i)
        manifest = fleet_manifest_valid(path, world_size)
        if manifest is not None:
            return round_i, path, manifest
    return None


def latest_slice_valid_fleet_manifest(
        d: str, world_size: Optional[int], lost_ranks: Tuple[int, ...]
) -> Optional[Tuple[int, str, Dict]]:
    """Newest SLICE-valid round in directory ``d`` for a replacement of
    the ranks in ``lost_ranks`` (docs/ROBUSTNESS.md "Slice-granular
    recovery"): the manifest must parse, its snapshot verify, and every
    SURVIVING rank's ack be present and matching — the lost slice's own
    acks are not required (its members died, possibly before acking the
    newest round the survivors confirmed).  Returns
    ``(round, manifest_path, manifest)`` or None."""
    try:
        entries = os.listdir(d)
    except OSError:
        return None
    rounds = []
    for name in entries:
        m = _FLEET_MANIFEST_RE.match(name)
        if m is not None:
            rounds.append(int(m.group("it")))
    lost = tuple(int(r) for r in lost_ranks)
    for round_i in sorted(rounds, reverse=True):
        path = fleet_manifest_path(d, round_i)
        manifest = fleet_manifest_valid(path, world_size,
                                        exclude_ranks=lost)
        if manifest is not None:
            return round_i, path, manifest
    return None


def prune_fleet_checkpoints(d: str, keep: int) -> List[int]:
    """Fleet-side retention: drop whole rounds (snapshot + manifest +
    acks) beyond the newest ``keep``, never the newest fleet-VALID round.
    Returns the pruned round numbers."""
    if keep <= 0:
        return []
    try:
        entries = os.listdir(d)
    except OSError:
        return []
    rounds = set()
    for name in entries:
        m = _FLEET_MANIFEST_RE.match(name)
        if m is not None:
            rounds.add(int(m.group("it")))
        sm = _SNAPSHOT_RE.match(name)
        if sm is not None and sm.group("prefix") == "fleet":
            rounds.add(int(sm.group("it")))
    ordered = sorted(rounds, reverse=True)
    newest_valid = latest_valid_fleet_manifest(d)
    keep_round = newest_valid[0] if newest_valid else None
    pruned: List[int] = []
    for round_i in ordered[keep:]:
        if round_i == keep_round:
            continue
        victims = [fleet_snapshot_path(d, round_i),
                   fleet_manifest_path(d, round_i)]
        victims += [os.path.join(d, n) for n in entries
                    if n.startswith(f"fleet.manifest_iter_{round_i}.ack.")]
        for path in victims:
            try:
                os.unlink(path)
            except OSError:
                pass
        pruned.append(round_i)
    if pruned:
        from ..obs import metrics as _obs

        _obs.counter("fleet_checkpoints_pruned_total").inc(len(pruned))
        _obs.event("fleet_checkpoint_prune", kept=keep, pruned=pruned)
    return pruned
