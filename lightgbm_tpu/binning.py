"""Host-side feature binning.

TPU-native re-design of the reference's binning layer
(reference: src/io/bin.cpp -> BinMapper::FindBin, GreedyFindBin;
include/LightGBM/bin.h -> MissingType).  Binning runs once on the host in
numpy; training then operates purely on the device-resident binned matrix
(uint8/int16), which is the TPU-first analogue of DenseBin.

Semantics preserved from the reference:
  * distinct-value fast path: if #distinct <= max_bin, one bin per value with
    boundaries at midpoints;
  * otherwise greedy equal-count binning honoring min_data_in_bin;
  * MissingType {None, Zero, NaN}: NaN values get their own bin placed LAST;
  * a dedicated zero bin when zero_as_missing=False but zeros dominate is not
    modelled separately (the quantile path handles it);
  * categorical: categories ordered by frequency, rare categories folded into
    bin 0 (reference: BinMapper categorical value->bin map).
  * real-valued split thresholds are reconstructed from bin upper bounds
    exactly as the reference does (Tree stores bin uppers so that the decision
    `value <= threshold` reproduces the binned decision `bin <= thr_bin`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

_KZERO_THRESHOLD = 1e-35  # reference: bin.cpp kZeroThreshold


@dataclass
class BinMapper:
    """Per-feature value->bin mapping (reference: BinMapper in bin.cpp)."""

    upper_bounds: np.ndarray  # (num_non_missing_bins,) float64; last == +inf
    missing_type: int = MISSING_NONE
    is_categorical: bool = False
    categories: Optional[np.ndarray] = None  # category value per bin (categorical only)
    min_value: float = 0.0
    max_value: float = 0.0

    @property
    def num_bins(self) -> int:
        """Total bins including the trailing missing bin if present."""
        n = len(self.upper_bounds) if not self.is_categorical else len(self.categories)
        if self.missing_type != MISSING_NONE:
            n += 1
        return n

    @property
    def missing_bin(self) -> int:
        """Index of the missing bin (NaN bin, or the zero/NaN bin when
        zero_as_missing), or -1 when the feature has no missing stream."""
        if self.missing_type != MISSING_NONE:
            return self.num_bins - 1
        return -1

    @property
    def is_trivial(self) -> bool:
        return self.num_bins <= 1

    def transform(self, values: np.ndarray) -> np.ndarray:
        """Map raw values -> bin indices (vectorized)."""
        values = np.asarray(values, dtype=np.float64)
        if self.is_categorical:
            # categories[b] is the raw value for bin b; build reverse map
            out = np.zeros(values.shape, dtype=np.int32)
            cat_to_bin = {float(c): b for b, c in enumerate(self.categories)}
            flat = values.ravel()
            res = np.fromiter(
                (cat_to_bin.get(v if not np.isnan(v) else -1.0, 0) for v in flat),
                dtype=np.int32,
                count=flat.size,
            )
            out = res.reshape(values.shape)
            if self.missing_type == MISSING_NAN:
                out[np.isnan(values)] = self.missing_bin
            return out
        # bin = first index with value <= upper_bounds[bin]
        bins = np.searchsorted(self.upper_bounds, values, side="left").astype(np.int32)
        np.clip(bins, 0, len(self.upper_bounds) - 1, out=bins)
        if self.missing_type == MISSING_NAN:
            bins[np.isnan(values)] = self.missing_bin
        elif self.missing_type == MISSING_ZERO:
            # zero_as_missing: zeros AND NaNs share the missing bin (reference:
            # MissingType::Zero routes both to the default bin)
            bins[np.isnan(values) | (np.abs(values) <= _KZERO_THRESHOLD)] = self.missing_bin
        return bins

    def bin_to_threshold(self, bin_idx: int) -> float:
        """Real-valued threshold for `bin <= bin_idx -> left` (reference:
        BinMapper::BinToValue used by Tree::Split when recording thresholds)."""
        ub = float(self.upper_bounds[bin_idx])
        if np.isinf(ub):
            ub = float(np.finfo(np.float64).max)
        return ub


def _greedy_equal_count_bounds(
    sorted_values: np.ndarray, counts: np.ndarray, max_bin: int, min_data_in_bin: int, total_cnt: int
) -> np.ndarray:
    """Greedy equal-frequency boundaries over (distinct value, count) pairs
    (reference: bin.cpp GreedyFindBin).  Returns upper bounds (last = +inf)."""
    num_distinct = len(sorted_values)
    if num_distinct <= max_bin:
        # one bin per distinct value; but respect min_data_in_bin by merging
        bounds = []
        cur = 0
        cum = np.cumsum(counts)
        for i in range(num_distinct - 1):
            cur += counts[i]
            rest = total_cnt - cum[i]
            # close the bin only when it is full enough AND the remainder can
            # still fill a bin of its own (otherwise fold the tail in)
            if cur >= min_data_in_bin and rest >= min_data_in_bin:
                bounds.append((sorted_values[i] + sorted_values[i + 1]) / 2.0)
                cur = 0
        bounds.append(np.inf)
        return np.asarray(bounds, dtype=np.float64)
    # too many distinct values: equal-count greedy
    max_bin = max(1, max_bin)
    mean_bin_size = max(total_cnt / max_bin, float(min_data_in_bin))
    # values with huge count get their own bin
    is_big = counts >= mean_bin_size
    rest_cnt = total_cnt - counts[is_big].sum()
    rest_bins = max_bin - int(is_big.sum())
    if rest_bins > 0:
        mean_bin_size = max(rest_cnt / rest_bins, float(min_data_in_bin))
    bounds = []
    cur = 0.0
    for i in range(num_distinct - 1):
        cur += counts[i]
        if is_big[i] or cur >= mean_bin_size or (i + 1 < num_distinct and is_big[i + 1] and cur > 0):
            bounds.append((sorted_values[i] + sorted_values[i + 1]) / 2.0)
            cur = 0.0
            if len(bounds) >= max_bin - 1:
                break
    bounds.append(np.inf)
    return np.unique(np.asarray(bounds, dtype=np.float64))


def find_bin(
    values: np.ndarray,
    max_bin: int = 255,
    min_data_in_bin: int = 3,
    use_missing: bool = True,
    zero_as_missing: bool = False,
    is_categorical: bool = False,
    min_data_per_group: int = 100,
    forced_bounds: Sequence[float] = (),
    num_implicit_zeros: int = 0,
) -> BinMapper:
    """Construct a BinMapper from (a sample of) one feature's values
    (reference: BinMapper::FindBin in src/io/bin.cpp).

    num_implicit_zeros: count of exact-0.0 values NOT present in `values` —
    the sparse-ingestion path passes only a column's stored (nonzero) entries
    plus this count, mirroring the reference's FindBin(total_sample_cnt >
    len(values)) contract for SparseBin construction."""
    values = np.asarray(values, dtype=np.float64).ravel()
    nan_mask = np.isnan(values)
    has_nan = bool(nan_mask.any())

    if is_categorical:
        clean = values[~nan_mask].astype(np.int64)
        cats, counts = np.unique(clean, return_counts=True)
        if num_implicit_zeros > 0:
            zi = np.searchsorted(cats, 0)
            if zi < len(cats) and cats[zi] == 0:
                counts = counts.copy()
                counts[zi] += num_implicit_zeros
            else:
                cats = np.insert(cats, zi, 0)
                counts = np.insert(counts, zi, num_implicit_zeros)
        order = np.argsort(-counts, kind="stable")
        cats, counts = cats[order], counts[order]
        # cap category count at max_bin (rare cats fold to the most frequent bin 0)
        cats = cats[:max_bin]
        missing_type = MISSING_NAN if (use_missing and has_nan) else MISSING_NONE
        return BinMapper(
            upper_bounds=np.asarray([np.inf]),
            missing_type=missing_type,
            is_categorical=True,
            categories=cats.astype(np.float64),
            min_value=float(cats.min()) if len(cats) else 0.0,
            max_value=float(cats.max()) if len(cats) else 0.0,
        )

    if zero_as_missing and use_missing:
        # zeros (and NaN) both become the missing value stream — implicit
        # (sparse-stored) zeros join it too
        zero_mask = np.abs(values) <= _KZERO_THRESHOLD
        nan_mask = nan_mask | zero_mask
        has_nan = bool(nan_mask.any()) or num_implicit_zeros > 0
        missing_type = MISSING_ZERO if has_nan else MISSING_NONE
        num_implicit_zeros = 0
    else:
        missing_type = MISSING_NAN if (use_missing and has_nan) else MISSING_NONE

    clean = values[~nan_mask]
    if len(clean) == 0 and num_implicit_zeros == 0:
        return BinMapper(upper_bounds=np.asarray([np.inf]), missing_type=missing_type)

    sorted_vals, counts = np.unique(clean, return_counts=True)
    if num_implicit_zeros > 0:
        zi = np.searchsorted(sorted_vals, 0.0)
        if zi < len(sorted_vals) and sorted_vals[zi] == 0.0:
            counts = counts.copy()
            counts[zi] += num_implicit_zeros
        else:
            sorted_vals = np.insert(sorted_vals, zi, 0.0)
            counts = np.insert(counts, zi, num_implicit_zeros)
    n_avail = max_bin - (1 if missing_type != MISSING_NONE else 0)
    n_avail = max(n_avail, 1)
    if len(forced_bounds):
        # forced bin boundaries from forcedbins_filename (reference:
        # bin.cpp BinMapper::FindBin forced_upper_bounds / DatasetLoader's
        # forced-bins JSON): the listed bounds become boundaries verbatim
        # and the remaining budget is filled greedily.
        forced = np.unique(np.asarray(forced_bounds, dtype=np.float64))
        forced = forced[: n_avail - 1]
        rest = max(n_avail - len(forced), 1)
        greedy = _greedy_equal_count_bounds(
            sorted_vals, counts, rest, min_data_in_bin, total_cnt=int(counts.sum())
        )
        bounds = np.unique(np.concatenate([forced, greedy]))
        if len(bounds) > n_avail:
            # keep all forced bounds + the largest greedy ones (incl. +inf)
            extra = np.setdiff1d(bounds, forced)[-(n_avail - len(forced)):]
            bounds = np.unique(np.concatenate([forced, extra]))
        if not np.isinf(bounds[-1]):
            bounds = np.append(bounds, np.inf)
    else:
        bounds = _greedy_equal_count_bounds(
            sorted_vals, counts, n_avail, min_data_in_bin, total_cnt=int(counts.sum())
        )
    mapper = BinMapper(
        upper_bounds=bounds,
        missing_type=MISSING_NAN if missing_type == MISSING_NAN else missing_type,
        min_value=float(sorted_vals[0]),
        max_value=float(sorted_vals[-1]),
    )
    return mapper


@dataclass
class DatasetBinner:
    """All-features binner; produces the device-ready binned matrix.

    TPU-first layout decision: the binned matrix is a dense (N, F) int array
    padded to a uniform per-dataset max bin count, which keeps histogram
    scatter indices affine (f * B + bin) — the analogue of the reference's
    FeatureGroup bin offsets (src/io/feature_group.h) without ragged groups.
    """

    mappers: List[BinMapper] = field(default_factory=list)

    @property
    def num_features(self) -> int:
        return len(self.mappers)

    @property
    def max_num_bins(self) -> int:
        return max((m.num_bins for m in self.mappers), default=1)

    @property
    def num_bins_per_feature(self) -> np.ndarray:
        return np.asarray([m.num_bins for m in self.mappers], dtype=np.int32)

    @property
    def missing_bin_per_feature(self) -> np.ndarray:
        return np.asarray([m.missing_bin for m in self.mappers], dtype=np.int32)

    @property
    def categorical_mask(self) -> np.ndarray:
        return np.asarray([m.is_categorical for m in self.mappers], dtype=bool)

    @classmethod
    def fit(
        cls,
        data: np.ndarray,
        max_bin: int = 255,
        min_data_in_bin: int = 3,
        sample_cnt: int = 200000,
        use_missing: bool = True,
        zero_as_missing: bool = False,
        categorical_features: Sequence[int] = (),
        max_bin_by_feature: Sequence[int] = (),
        seed: int = 1,
        forced_bins: Optional[dict] = None,
    ) -> "DatasetBinner":
        data = np.asarray(data, dtype=np.float64)
        n, f = data.shape
        if n > sample_cnt:
            rng = np.random.RandomState(seed)
            idx = rng.choice(n, size=sample_cnt, replace=False)
            sample = data[idx]
        else:
            sample = data
        cats = set(int(c) for c in categorical_features)
        forced_bins = forced_bins or {}
        mappers = []
        for j in range(f):
            mb = int(max_bin_by_feature[j]) if len(max_bin_by_feature) == f else max_bin
            mappers.append(
                find_bin(
                    sample[:, j],
                    max_bin=mb,
                    min_data_in_bin=min_data_in_bin,
                    use_missing=use_missing,
                    zero_as_missing=zero_as_missing,
                    is_categorical=j in cats,
                    forced_bounds=forced_bins.get(j, ()),
                )
            )
        return cls(mappers=mappers)

    def transform(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data, dtype=np.float64)
        n, f = data.shape
        assert f == self.num_features, (f, self.num_features)
        dtype = np.uint8 if self.max_num_bins <= 256 else np.int32
        out = np.empty((n, f), dtype=dtype)
        for j, m in enumerate(self.mappers):
            out[:, j] = m.transform(data[:, j]).astype(dtype)
        return out

    @classmethod
    def fit_sparse(
        cls,
        csc,  # scipy.sparse CSC matrix
        max_bin: int = 255,
        min_data_in_bin: int = 3,
        sample_cnt: int = 200000,
        use_missing: bool = True,
        zero_as_missing: bool = False,
        categorical_features: Sequence[int] = (),
        max_bin_by_feature: Sequence[int] = (),
        seed: int = 1,
        forced_bins: Optional[dict] = None,
    ) -> "DatasetBinner":
        """Fit bin mappers from a CSC matrix WITHOUT densifying (reference:
        DatasetLoader::ConstructBinMappersFromSampleData over SparseBin
        columns — stored nonzeros plus an implicit-zero count per feature)."""
        n, f = csc.shape
        if n > sample_cnt:
            rng = np.random.RandomState(seed)
            idx = np.sort(rng.choice(n, size=sample_cnt, replace=False))
            csc = csc[idx]
            n = sample_cnt
        cats = set(int(c) for c in categorical_features)
        forced_bins = forced_bins or {}
        indptr, data = csc.indptr, csc.data
        mappers = []
        for j in range(f):
            vals = np.asarray(data[indptr[j]:indptr[j + 1]], np.float64)
            mb = int(max_bin_by_feature[j]) if len(max_bin_by_feature) == f else max_bin
            mappers.append(
                find_bin(
                    vals,
                    max_bin=mb,
                    min_data_in_bin=min_data_in_bin,
                    use_missing=use_missing,
                    zero_as_missing=zero_as_missing,
                    is_categorical=j in cats,
                    forced_bounds=forced_bins.get(j, ()),
                    num_implicit_zeros=int(n - len(vals)),
                )
            )
        return cls(mappers=mappers)

    def transform_sparse(self, csc) -> np.ndarray:
        """CSC matrix -> dense BINNED (N, F) uint8/int32 — the raw float
        matrix is never materialized (the binned matrix is 8x smaller than
        a float64 densify and is the layout training uses anyway)."""
        n, f = csc.shape
        assert f == self.num_features, (f, self.num_features)
        dtype = np.uint8 if self.max_num_bins <= 256 else np.int32
        out = np.empty((n, f), dtype=dtype)
        indptr, indices, data = csc.indptr, csc.indices, csc.data
        for j, m in enumerate(self.mappers):
            zero_bin = int(m.transform(np.zeros(1))[0])
            out[:, j] = zero_bin
            lo, hi = indptr[j], indptr[j + 1]
            if hi > lo:
                out[indices[lo:hi], j] = m.transform(
                    np.asarray(data[lo:hi], np.float64)
                ).astype(dtype)
        return out
