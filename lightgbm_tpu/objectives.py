"""Objective functions: gradients/hessians as pure jitted array functions.

TPU-native re-design of the reference's objective layer
(reference: src/objective/objective_function.cpp factory,
regression_objective.hpp, binary_objective.hpp, multiclass_objective.hpp,
xentropy_objective.hpp, rank_objective.hpp, and their CUDA twins under
src/objective/cuda/ — here one implementation serves every backend since XLA
compiles the same code for TPU and CPU).

Each objective exposes:
  * get_gradients(score, label, weight) -> (grad, hess), both (N,) or (N, K)
  * boost_from_score(label, weight) -> float init score (reference:
    ObjectiveFunction::BoostFromScore, used when boost_from_average=true)
  * convert_output(score) -> prediction-space outputs (reference:
    ObjectiveFunction::ConvertOutput)
  * renew_tree_output(...) optional per-leaf refit (L1/quantile/MAPE/Huber —
    reference: RenewTreeOutput); implemented with masked per-leaf weighted
    quantiles on device.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config

Array = jnp.ndarray


class Objective:
    """Base class; subclasses are lightweight param holders — all math is in
    jit-compiled static methods closed over Python-float hyperparams."""

    name = "custom"
    num_model_per_iteration = 1
    need_renew = False
    is_constant_hessian = False
    # get_gradients is a pure jnp function of (score, label, weight) and may
    # be traced inside the fused training step (models/gbdt.py); objectives
    # with per-iteration host state must set this False (or override
    # is_fusable for instance-dependent purity)
    fusable = True

    def is_fusable(self) -> bool:
        return self.fusable

    # fused-state protocol: objectives with per-iteration device state (e.g.
    # LambdaRank position biases) stay fusable by threading that state
    # through the fused step as an explicit carry instead of mutating self
    # in-trace.  fused_state() -> carry (or None); fused_gradients is PURE
    # and returns (grad, hess, new_carry); set_fused_state writes the carry
    # back after the step retires.
    def fused_state(self):
        return None

    def fused_gradients(self, score: Array, label: Array,
                        weight: Optional[Array], state):
        g, h = self.get_gradients(score, label, weight)
        return g, h, state

    def set_fused_state(self, state) -> None:
        pass

    def __init__(self, cfg: Config):
        self.cfg = cfg

    def get_gradients(self, score: Array, label: Array, weight: Optional[Array]) -> Tuple[Array, Array]:
        raise NotImplementedError

    def boost_from_score(self, label: Array, weight: Optional[Array]) -> float:
        return 0.0

    def convert_output(self, score: Array) -> Array:
        return score

    def renew_tree_output(self, leaf_pred, label, weight, score, leaf_id, num_leaves) -> Optional[Array]:
        return None

    def _w(self, weight, label):
        return jnp.ones_like(label) if weight is None else weight


class RegressionL2(Objective):
    """reference: RegressionL2loss in regression_objective.hpp.

    reg_sqrt (plain L2 only, as in the reference): the model is fit to
    sign(y)*sqrt(|y|) and predictions are squared back in ConvertOutput —
    metrics see original-scale outputs through GBDT._converted."""

    name = "regression"
    is_constant_hessian = True

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.sqrt = bool(cfg.reg_sqrt) and type(self) is RegressionL2

    def _t(self, label):
        if self.sqrt:
            return jnp.sign(label) * jnp.sqrt(jnp.abs(label))
        return label

    def get_gradients(self, score, label, weight):
        w = self._w(weight, label)
        return (score - self._t(label)) * w, w

    def boost_from_score(self, label, weight):
        label = self._t(jnp.asarray(label))
        if weight is None:
            return float(jnp.mean(label))
        return float(jnp.sum(label * weight) / jnp.sum(weight))

    def convert_output(self, score):
        if self.sqrt:
            return jnp.sign(score) * score * score
        return score


class RegressionL1(Objective):
    """reference: RegressionL1loss — gradient is sign, leaf renewed to the
    weighted median of residuals (RenewTreeOutput with percentile 0.5)."""

    name = "regression_l1"
    need_renew = True
    is_constant_hessian = True

    def get_gradients(self, score, label, weight):
        w = self._w(weight, label)
        return jnp.sign(score - label) * w, w

    def boost_from_score(self, label, weight):
        return float(_weighted_quantile_np(np.asarray(label), None if weight is None else np.asarray(weight), 0.5))

    def renew_tree_output(self, leaf_pred, label, weight, score, leaf_id, num_leaves):
        residual = label - score
        return _per_leaf_weighted_quantile(residual, self._w(weight, label), leaf_id, num_leaves, 0.5)


class RegressionHuber(RegressionL2):
    """reference: RegressionHuberLoss (alpha)."""

    name = "huber"
    need_renew = False
    is_constant_hessian = True

    def get_gradients(self, score, label, weight):
        a = self.cfg.alpha
        w = self._w(weight, label)
        diff = score - label
        g = jnp.where(jnp.abs(diff) <= a, diff, jnp.sign(diff) * a)
        return g * w, w


class RegressionFair(Objective):
    """reference: RegressionFairLoss (fair_c)."""

    name = "fair"
    is_constant_hessian = False

    def get_gradients(self, score, label, weight):
        c = self.cfg.fair_c
        w = self._w(weight, label)
        x = score - label
        g = c * x / (jnp.abs(x) + c)
        h = c * c / ((jnp.abs(x) + c) ** 2)
        return g * w, h * w


class RegressionPoisson(Objective):
    """reference: RegressionPoissonLoss — scores in log space; hessian uses
    poisson_max_delta_step safeguard (see sklearn test_compare_lightgbm.py:101
    for the behavioral consequence)."""

    name = "poisson"

    def get_gradients(self, score, label, weight):
        w = self._w(weight, label)
        g = (jnp.exp(score) - label) * w
        h = jnp.exp(score + self.cfg.poisson_max_delta_step) * w
        return g, h

    def boost_from_score(self, label, weight):
        w = 1.0 if weight is None else weight
        mean = float(jnp.sum(label * w) / jnp.sum(jnp.ones_like(label) * w))
        return float(np.log(max(mean, 1e-9)))

    def convert_output(self, score):
        return jnp.exp(score)


class RegressionGamma(RegressionPoisson):
    """reference: RegressionGammaLoss."""

    name = "gamma"

    def get_gradients(self, score, label, weight):
        w = self._w(weight, label)
        g = (1.0 - label * jnp.exp(-score)) * w
        h = label * jnp.exp(-score) * w
        return g, h


class RegressionTweedie(RegressionPoisson):
    """reference: RegressionTweedieLoss (tweedie_variance_power rho)."""

    name = "tweedie"

    def get_gradients(self, score, label, weight):
        rho = self.cfg.tweedie_variance_power
        w = self._w(weight, label)
        exp1 = jnp.exp((1.0 - rho) * score)
        exp2 = jnp.exp((2.0 - rho) * score)
        g = (-label * exp1 + exp2) * w
        h = (-label * (1.0 - rho) * exp1 + (2.0 - rho) * exp2) * w
        return g, h


class RegressionQuantile(Objective):
    """reference: RegressionQuantileloss (alpha), leaf renewed to the alpha
    quantile of residuals."""

    name = "quantile"
    need_renew = True
    is_constant_hessian = True

    def get_gradients(self, score, label, weight):
        a = self.cfg.alpha
        w = self._w(weight, label)
        g = jnp.where(score >= label, 1.0 - a, -a)
        return g * w, w

    def boost_from_score(self, label, weight):
        return float(_weighted_quantile_np(np.asarray(label), None if weight is None else np.asarray(weight), self.cfg.alpha))

    def renew_tree_output(self, leaf_pred, label, weight, score, leaf_id, num_leaves):
        residual = label - score
        return _per_leaf_weighted_quantile(residual, self._w(weight, label), leaf_id, num_leaves, self.cfg.alpha)


class RegressionMAPE(Objective):
    """reference: RegressionMAPELOSS — label-scaled weights, median renew."""

    name = "mape"
    need_renew = True
    is_constant_hessian = True

    def get_gradients(self, score, label, weight):
        w = self._w(weight, label)
        scale = w / jnp.maximum(1.0, jnp.abs(label))
        scale = scale / jnp.mean(scale)
        return jnp.sign(score - label) * scale, scale

    def boost_from_score(self, label, weight):
        # same 1/max(1,|label|)-scaled weights as the boosting rounds
        # (reference: RegressionMAPELOSS::BoostFromScore weighted percentile)
        lab = np.asarray(label, np.float64)
        w = np.ones_like(lab) if weight is None else np.asarray(weight, np.float64)
        w = w / np.maximum(1.0, np.abs(lab))
        return float(_weighted_quantile_np(lab, w, 0.5))

    def renew_tree_output(self, leaf_pred, label, weight, score, leaf_id, num_leaves):
        w = self._w(weight, label) / jnp.maximum(1.0, jnp.abs(label))
        return _per_leaf_weighted_quantile(label - score, w, leaf_id, num_leaves, 0.5)


class BinaryLogloss(Objective):
    """reference: BinaryLogloss in binary_objective.hpp.

    grad = sigmoid_scale * (p - y) * label_weight; hess = scale^2 p (1-p) w.
    is_unbalance / scale_pos_weight set the positive-label weight.
    """

    name = "binary"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.pos_weight = cfg.scale_pos_weight

    def prepare(self, label: np.ndarray, weight) -> None:
        if self.cfg.is_unbalance:
            pos = float(np.sum(label > 0))
            neg = float(len(label) - pos)
            if pos > 0 and neg > 0:
                self.pos_weight = neg / pos

    def get_gradients(self, score, label, weight):
        sig = self.cfg.sigmoid
        w = self._w(weight, label)
        y = jnp.where(label > 0, 1.0, -1.0)
        lw = jnp.where(label > 0, self.pos_weight, 1.0) * w
        response = -y * sig / (1.0 + jnp.exp(y * sig * score))
        grad = response * lw
        hess = jnp.abs(response) * (sig - jnp.abs(response)) * lw
        return grad, hess

    def boost_from_score(self, label, weight):
        if weight is None:
            p = float(jnp.mean(jnp.where(label > 0, 1.0, 0.0)))
        else:
            p = float(jnp.sum(jnp.where(label > 0, weight, 0.0)) / jnp.sum(weight))
        p = min(max(p, 1e-15), 1.0 - 1e-15)
        return float(np.log(p / (1.0 - p)) / self.cfg.sigmoid)

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.cfg.sigmoid * score))


class MulticlassSoftmax(Objective):
    """reference: MulticlassSoftmax — K trees per iteration; hessian carries
    the factor-2 convention (sklearn utils.py:69-77 documents it)."""

    name = "multiclass"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.num_model_per_iteration = cfg.num_class

    def get_gradients(self, score, label, weight):
        # score: (N, K); label: (N,) int class ids
        k = self.cfg.num_class
        w = self._w(weight, label)[:, None]
        p = jax.nn.softmax(score, axis=-1)
        y = jax.nn.one_hot(label.astype(jnp.int32), k, dtype=score.dtype)
        grad = (p - y) * w
        hess = 2.0 * p * (1.0 - p) * w
        return grad, hess

    def convert_output(self, score):
        return jax.nn.softmax(score, axis=-1)


class MulticlassOVA(Objective):
    """reference: MulticlassOVA — K independent binary problems."""

    name = "multiclassova"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.num_model_per_iteration = cfg.num_class
        self.binary = BinaryLogloss(cfg)

    def get_gradients(self, score, label, weight):
        k = self.cfg.num_class
        y = jax.nn.one_hot(label.astype(jnp.int32), k, dtype=score.dtype)
        grads, hesss = jax.vmap(
            lambda s, yy: self.binary.get_gradients(s, yy, weight), in_axes=(1, 1), out_axes=1
        )(score, y)
        return grads, hesss

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.cfg.sigmoid * score))


class CrossEntropy(Objective):
    """reference: CrossEntropy in xentropy_objective.hpp (labels in [0,1])."""

    name = "cross_entropy"

    def get_gradients(self, score, label, weight):
        w = self._w(weight, label)
        p = 1.0 / (1.0 + jnp.exp(-score))
        return (p - label) * w, p * (1.0 - p) * w

    def boost_from_score(self, label, weight):
        p = float(jnp.mean(label)) if weight is None else float(
            jnp.sum(label * weight) / jnp.sum(weight)
        )
        p = min(max(p, 1e-15), 1 - 1e-15)
        return float(np.log(p / (1 - p)))

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-score))


class _RankingObjective(Objective):
    """Shared per-query padding machinery (reference: RankingObjective in
    rank_objective.hpp — per-query parallel gradient computation).  Queries
    are laid out as a dense (Q, S) block padded to the longest query; masked
    lanes contribute zeros (SURVEY.md §10.3 item 3)."""

    # per-iteration host state (xendcg's RNG iteration counter) must not be
    # baked into a traced step; LambdaRank overrides this — its position
    # biases ride the fused step as an explicit carry (fused_state protocol)
    fusable = False

    def set_query(self, query_boundaries: np.ndarray, labels: np.ndarray):
        from .metrics import pad_queries

        self.query_boundaries = np.asarray(query_boundaries)
        nq = len(self.query_boundaries) - 1
        lens = np.diff(self.query_boundaries)
        self.max_query = int(lens.max()) if nq else 0
        pad_idx, pad_mask = pad_queries(self.query_boundaries)
        self._pad_idx = jnp.asarray(pad_idx)
        self._pad_mask = jnp.asarray(pad_mask)


class RankXENDCG(_RankingObjective):
    """reference: RankXENDCGObjective in rank_xendcg_objective.hpp — the
    listwise cross-entropy NDCG surrogate (Bruch 2020, "An Alternative Cross
    Entropy Loss for Learning-to-Rank").

    Per query: rho = softmax(scores); phi_i = 2^label_i − u_i with u_i ~
    Uniform(0,1) resampled each iteration (objective_seed); then the
    three-term gradient
        l1_i = rho_i − phi_i / Σphi
        l2_i = l1_i − rho_i · Σl1
        λ_i  = l2_i − rho_i · Σl2,   h_i = rho_i (1 − rho_i).
    """

    name = "rank_xendcg"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self._iter = 0
        self._seed = int(getattr(cfg, "objective_seed", 5))

    def get_gradients(self, score, label, weight):
        idx, msk = self._pad_idx, self._pad_mask
        s = score[idx.reshape(-1)].reshape(idx.shape)
        l = label[idx.reshape(-1)].reshape(idx.shape)
        key = jax.random.PRNGKey(self._seed + self._iter)
        self._iter += 1
        u = jax.random.uniform(key, idx.shape, dtype=jnp.float32)
        g, h = _xendcg_query(s, l, msk, u)
        # .add, not .set: pad_idx's padding lanes all alias row 0 and carry
        # masked-out zeros — a duplicate-index .set would zero row 0's grads
        grad = jnp.zeros_like(score).at[idx.reshape(-1)].add(g.reshape(-1))
        hess = jnp.zeros_like(score).at[idx.reshape(-1)].add(h.reshape(-1))
        return grad, hess


@jax.jit
def _xendcg_query(scores, labels, mask, u):
    """Vectorized XE-NDCG gradients over padded queries: (Q, S) in/out."""
    neg_inf = jnp.float32(-1e30)
    masked = jnp.where(mask, scores, neg_inf)
    rho = jax.nn.softmax(masked, axis=1)
    rho = jnp.where(mask, rho, 0.0)
    phi = jnp.where(mask, jnp.exp2(labels.astype(jnp.float32)) - u, 0.0)
    denom = jnp.maximum(jnp.sum(phi, axis=1, keepdims=True), 1e-20)
    l1 = rho - phi / denom
    l2 = l1 - rho * jnp.sum(l1, axis=1, keepdims=True)
    lam = l2 - rho * jnp.sum(l2, axis=1, keepdims=True)
    hess = rho * (1.0 - rho)
    return jnp.where(mask, lam, 0.0), jnp.where(mask, hess, 0.0)


class CrossEntropyLambda(Objective):
    """reference: CrossEntropyLambda in xentropy_objective.hpp ("xentlambda"):
    alternative parameterization of cross entropy where the (optional) weight
    scales the Poisson-style intensity lambda = w * log1p(e^f); the label is
    a probability in [0, 1].  Gradients/hessians are derived by elementwise
    jax autodiff of the stable loss expression (the reference hand-derives
    the same closed forms)."""

    name = "cross_entropy_lambda"

    @staticmethod
    def _loss(f, t, w):
        lam = w * jnp.log1p(jnp.exp(f))
        # -log(1 - e^-lam) stably
        log1m = jnp.log(-jnp.expm1(-jnp.maximum(lam, 1e-30)))
        return (1.0 - t) * lam - t * log1m

    def get_gradients(self, score, label, weight):
        w = jnp.ones_like(score) if weight is None else weight
        g = jax.vmap(jax.grad(self._loss))(score, label, w)
        h = jax.vmap(jax.grad(jax.grad(self._loss)))(score, label, w)
        return g, jnp.maximum(h, 1e-8)

    def convert_output(self, score):
        # yhat = 1 - exp(-log1p(e^f)) = sigmoid(f) at unit weight
        return jax.nn.sigmoid(score)

    def boost_from_score(self, label, weight):
        p = float(jnp.clip(jnp.mean(label), 1e-6, 1 - 1e-6))
        return float(np.log(p / (1 - p)))


class LambdarankNDCG(_RankingObjective):
    """reference: LambdarankNDCG in rank_objective.hpp.

    Pairwise NDCG-weighted lambdas inside each query, truncated to
    `lambdarank_truncation_level`.  Queries are processed as padded fixed-width
    blocks (SURVEY.md §10.3 item 3): queries are bucketed by length and the
    pairwise (i, j) interaction computed as dense (Q, S, S) tensors — the
    TPU-friendly formulation of the reference's per-query scalar loops.
    """

    name = "lambdarank"
    # always fusable: plain lambdas are pure, and position-bias state rides
    # the fused step as a carry (fused_state protocol below)
    fusable = True

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.truncation = cfg.lambdarank_truncation_level
        self.norm = cfg.lambdarank_norm
        self.sigmoid = cfg.sigmoid if cfg.sigmoid > 0 else 1.0
        gains = cfg.label_gain
        if not gains:
            gains = [float(2**i - 1) for i in range(31)]
        self.label_gain = np.asarray(gains, dtype=np.float64)
        self._query_info = None  # set via set_query

    def set_query(self, query_boundaries: np.ndarray, labels: np.ndarray):
        """Precompute inverse max DCG per query (reference:
        inverse_max_dcgs_ in LambdarankNDCG::Init)."""
        from .metrics import dcg_at_k

        super().set_query(query_boundaries, labels)
        nq = len(self.query_boundaries) - 1
        inv = np.zeros(nq, dtype=np.float64)
        trunc = self.truncation
        for q in range(nq):
            lo, hi = self.query_boundaries[q], self.query_boundaries[q + 1]
            ql = labels[lo:hi]
            ideal = np.sort(ql)[::-1]
            m = dcg_at_k(ideal, min(len(ql), trunc), self.label_gain)
            inv[q] = 1.0 / m if m > 0 else 0.0
        self.inverse_max_dcg = inv

    def set_positions(self, positions: np.ndarray):
        """Enable position-bias correction (reference: rank_objective.hpp —
        positions_/pos_biases_ and UpdatePositionBiasFactors).  The model
        score is augmented with a learned additive per-position bias during
        lambda computation; the biases themselves are refit each iteration
        with a Newton step regularized by
        lambdarank_position_bias_regularization, so the TREES learn the
        position-debiased ranking while the biases absorb presentation
        effects (unbiased LambdaRank)."""
        positions = np.asarray(positions, np.int64).ravel()
        idx = np.asarray(self._pad_idx)
        self._pos_pad = jnp.asarray(positions[idx])  # (Q, S)
        self.num_positions = int(positions.max()) + 1
        self.pos_bias = jnp.zeros((self.num_positions,), jnp.float32)
        self.pos_reg = float(getattr(self.cfg, "lambdarank_position_bias_regularization", 0.0))

    _pos_pad = None

    def _gradients_core(self, score, label, pos_bias):
        """PURE lambda computation: position bias enters as an argument and
        the refit bias is returned, so this body can trace inside the fused
        step with the bias as a carry."""
        idx, msk = self._pad_idx, self._pad_mask
        s = score[idx.reshape(-1)].reshape(idx.shape)
        l = label[idx.reshape(-1)].reshape(idx.shape)
        if pos_bias is not None:
            # scores seen by the lambda computation include the position bias
            s = s + jnp.where(msk, pos_bias[self._pos_pad], 0.0)
        gains = jnp.asarray(self.label_gain, dtype=jnp.float32)
        inv_mdcg = jnp.asarray(self.inverse_max_dcg, dtype=jnp.float32)
        g, h = _lambdarank_pairwise(
            s, l, msk, gains, inv_mdcg, self.sigmoid, self.truncation, self.norm
        )
        new_bias = pos_bias
        if pos_bias is not None:
            # Newton refit of the biases from this iteration's lambdas
            # (reference: UpdatePositionBiasFactors once per iteration)
            P = self.num_positions
            gm = jnp.where(msk, g, 0.0).reshape(-1)
            hm = jnp.where(msk, h, 0.0).reshape(-1)
            pp = self._pos_pad.reshape(-1)
            Gp = jnp.zeros((P,), jnp.float32).at[pp].add(gm)
            Hp = jnp.zeros((P,), jnp.float32).at[pp].add(hm)
            reg = self.pos_reg
            new_bias = pos_bias - (Gp + reg * pos_bias) / (Hp + reg + 1e-9)
        # .add, not .set: pad_idx's padding lanes all alias row 0 and carry
        # masked-out zeros — a duplicate-index .set would zero row 0's grads
        grad = jnp.zeros_like(score).at[idx.reshape(-1)].add(g.reshape(-1))
        hess = jnp.zeros_like(score).at[idx.reshape(-1)].add(h.reshape(-1))
        return grad, hess, new_bias

    def get_gradients(self, score, label, weight):
        bias = self.pos_bias if self._pos_pad is not None else None
        grad, hess, new_bias = self._gradients_core(score, label, bias)
        if self._pos_pad is not None:
            self.pos_bias = new_bias
        return grad, hess

    # fused-state protocol: the position biases ride the fused step as a
    # carry (reference: UpdatePositionBiasFactors runs once per iteration —
    # here that Newton refit happens in-trace and the carry is written back
    # when the step retires)
    def fused_state(self):
        return self.pos_bias if self._pos_pad is not None else None

    def fused_gradients(self, score, label, weight, state):
        return self._gradients_core(score, label, state)

    def set_fused_state(self, state) -> None:
        if state is not None:
            self.pos_bias = state


@functools.partial(jax.jit, static_argnames=("sigmoid", "truncation", "norm"))
def _lambdarank_pairwise(scores, labels, mask, label_gain, inv_mdcg, sigmoid, truncation, norm):
    """Dense pairwise lambda computation over padded queries.

    scores/labels/mask: (Q, S).  Returns (grad, hess): (Q, S).
    """
    q, s_len = scores.shape
    neg_inf = jnp.float32(-1e30)
    masked_scores = jnp.where(mask, scores, neg_inf)
    # rank of each item within its query by current score (descending)
    order = jnp.argsort(-masked_scores, axis=1, stable=True)  # (Q, S) item idx by rank
    ranks = jnp.argsort(order, axis=1)  # rank of each position

    lg = label_gain[jnp.clip(labels.astype(jnp.int32), 0, label_gain.shape[0] - 1)]
    lg = jnp.where(mask, lg, 0.0)
    disc = 1.0 / jnp.log2(ranks.astype(jnp.float32) + 2.0)
    disc = jnp.where(ranks < truncation, disc, jnp.where(mask, 0.0, 0.0))
    # keep pairs where at least one side ranks inside the truncation window
    in_window = ranks < truncation

    d_s = scores[:, :, None] - scores[:, None, :]
    d_gain = lg[:, :, None] - lg[:, None, :]
    d_disc = disc[:, :, None] - disc[:, None, :]
    delta_ndcg = jnp.abs(d_gain) * jnp.abs(d_disc) * inv_mdcg[:, None, None]
    better = (labels[:, :, None] > labels[:, None, :]) & mask[:, :, None] & mask[:, None, :]
    better = better & (in_window[:, :, None] | in_window[:, None, :])

    rho = 1.0 / (1.0 + jnp.exp(sigmoid * d_s))  # sigmoid(-sig*(si-sj))
    lam = sigmoid * rho * delta_ndcg
    hes = sigmoid * sigmoid * rho * (1.0 - rho) * delta_ndcg
    lam = jnp.where(better, lam, 0.0)
    hes = jnp.where(better, hes, 0.0)

    grad = -jnp.sum(lam, axis=2) + jnp.sum(jnp.swapaxes(lam, 1, 2), axis=2)
    hess = jnp.sum(hes, axis=2) + jnp.sum(jnp.swapaxes(hes, 1, 2), axis=2)

    if norm:
        total = jnp.sum(jnp.abs(lam), axis=(1, 2), keepdims=False)[:, None]
        scale = jnp.where(total > 0, jnp.log2(1.0 + total) / jnp.maximum(total, 1e-20), 1.0)
        grad = grad * scale
        hess = hess * scale
    grad = jnp.where(mask, grad, 0.0)
    hess = jnp.where(mask, hess, 0.0)
    return grad, hess


# ---------------------------------------------------------------------------
# per-leaf weighted quantile (for RenewTreeOutput objectives)
# ---------------------------------------------------------------------------
def _per_leaf_weighted_quantile(values, weights, leaf_id, num_leaves, q):
    """Weighted q-quantile of `values` within each leaf (masked, O(L * N log N)
    via one shared sort — reference: PercentileFun/WeightedPercentileFun in
    regression_objective.hpp)."""
    order = jnp.argsort(values)
    v = values[order]
    w = weights[order]
    lid = leaf_id[order]

    def one_leaf(leaf):
        m = (lid == leaf).astype(v.dtype) * w
        cum = jnp.cumsum(m)
        total = cum[-1]
        target = q * total
        # first index where cumulative weight >= target
        idx = jnp.searchsorted(cum, target, side="left")
        idx = jnp.clip(idx, 0, v.shape[0] - 1)
        return v[idx]

    return jax.vmap(one_leaf)(jnp.arange(num_leaves))


def _weighted_quantile_np(values, weights, q):
    order = np.argsort(values)
    v = values[order]
    if weights is None:
        # reference PercentileFun: midpoint convention for even counts at q=0.5
        n = len(v)
        if n == 0:
            return 0.0
        pos = q * (n - 1)
        lo = int(np.floor(pos))
        hi = int(np.ceil(pos))
        return 0.5 * (v[lo] + v[hi]) if hi != lo else float(v[lo])
    w = np.asarray(weights)[order]
    cum = np.cumsum(w)
    target = q * cum[-1]
    idx = int(np.searchsorted(cum, target, side="left"))
    return float(v[min(idx, len(v) - 1)])


# ---------------------------------------------------------------------------
_REGISTRY: Dict[str, Callable[[Config], Objective]] = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": RegressionHuber,
    "fair": RegressionFair,
    "poisson": RegressionPoisson,
    "gamma": RegressionGamma,
    "tweedie": RegressionTweedie,
    "quantile": RegressionQuantile,
    "mape": RegressionMAPE,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
        "lambdarank": LambdarankNDCG,
    "rank_xendcg": RankXENDCG,
}


def create_objective(cfg: Config) -> Optional[Objective]:
    """reference: ObjectiveFunction::CreateObjectiveFunction."""
    name = cfg.objective
    if name in ("none", "null", "custom", "na", ""):
        return None
    if name not in _REGISTRY:
        raise ValueError(f"Unknown objective: {name}")
    return _REGISTRY[name](cfg)
