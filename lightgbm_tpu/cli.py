"""Command-line driver: `python -m lightgbm_tpu config=train.conf [key=value ...]`.

Reference: src/main.cpp + src/application/application.cpp
(Application::{Run,LoadData,InitTrain,Train,Predict,ConvertModel}) and the
CLI config conventions from docs (config= file of `key = value` lines, CLI
`key=value` overrides, tasks train/predict/convert_model/refit).

Network params (num_machines, machines, local_listen_port, ...) are accepted
for config compatibility; distributed execution happens through JAX's mesh
runtime instead of socket linkers (SURVEY.md §3.6), so they only trigger an
informational message.
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .config import Config
from .engine import train as train_fn
from .io import load_data_file
from .utils.log import log_info, log_warning


def parse_config_file(path: str) -> Dict[str, str]:
    """LightGBM conf format: `key = value` per line, `#` comments."""
    out: Dict[str, str] = {}
    with open(path) as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def parse_argv(argv: List[str]) -> Dict[str, str]:
    params: Dict[str, str] = {}
    conf_file = None
    for tok in argv:
        if "=" not in tok:
            log_warning(f"ignoring malformed argument: {tok!r}")
            continue
        k, v = tok.split("=", 1)
        k = k.strip()
        if k in ("config", "config_file"):
            conf_file = v.strip()
        else:
            params[k] = v.strip()
    if conf_file:
        file_params = parse_config_file(conf_file)
        file_params.update(params)  # CLI overrides file (reference behavior)
        params = file_params
    return params


def _load_dataset(cfg: Config, path: str, params: Dict, reference=None) -> Dataset:
    if cfg.two_round:
        # streaming two-pass load (reference: two_round=true): the Dataset
        # takes the path and bins per chunk without a raw float matrix
        return Dataset(path, params=params, reference=reference)
    loaded = load_data_file(
        path,
        header=cfg.header,
        label_column=cfg.label_column,
        weight_column=cfg.weight_column,
        group_column=cfg.group_column,
        ignore_column=cfg.ignore_column,
    )
    return Dataset(
        loaded["data"],
        label=loaded["label"],
        weight=loaded["weight"],
        group=loaded["group"],
        feature_name=loaded["feature_names"],
        params=params,
        reference=reference,
    )


def run(argv: Optional[List[str]] = None) -> int:
    params = parse_argv(list(argv if argv is not None else sys.argv[1:]))
    cfg = Config.from_dict(params)
    if cfg.num_machines > 1:
        log_info(
            "num_machines > 1: distributed execution is provided by the JAX "
            "mesh runtime (jax.distributed + shard_map); socket/MPI network "
            "params are accepted for config compatibility and ignored."
        )
    task = cfg.task
    if task == "train":
        return _task_train(cfg, params)
    if task in ("predict", "prediction", "test"):
        return _task_predict(cfg, params)
    if task == "convert_model":
        return _task_convert(cfg)
    if task == "refit":
        return _task_refit(cfg, params)
    log_warning(f"unknown task {task!r}")
    return 1


def _task_train(cfg: Config, params: Dict) -> int:
    if not cfg.data:
        log_warning("task=train requires data=<file>")
        return 1
    train_set = _load_dataset(cfg, cfg.data, params)
    valid_sets = []
    valid_names = []
    for i, vpath in enumerate(cfg.valid if isinstance(cfg.valid, list) else [cfg.valid]):
        if not vpath:
            continue
        valid_sets.append(_load_dataset(cfg, vpath, params, reference=train_set))
        valid_names.append(f"valid_{i}")
    from .callback import log_evaluation

    init_model = cfg.input_model if cfg.input_model else None
    bst = train_fn(
        params,
        train_set,
        num_boost_round=cfg.num_iterations,
        valid_sets=valid_sets,
        valid_names=valid_names,
        init_model=init_model,
        callbacks=[log_evaluation(max(cfg.metric_freq, 1))],
    )
    bst.save_model(cfg.output_model)
    log_info(f"finished training; model written to {cfg.output_model}")
    return 0


def _task_predict(cfg: Config, params: Dict) -> int:
    if not cfg.input_model or not cfg.data:
        log_warning("task=predict requires input_model=<file> and data=<file>")
        return 1
    bst = Booster(model_file=cfg.input_model)
    loaded = load_data_file(
        cfg.data, header=cfg.header, label_column=cfg.label_column,
        weight_column=cfg.weight_column, group_column=cfg.group_column,
        ignore_column=cfg.ignore_column,
    )
    pred = bst.predict(
        loaded["data"],
        raw_score=cfg.predict_raw_score,
        pred_leaf=cfg.predict_leaf_index,
        pred_contrib=cfg.predict_contrib,
        num_iteration=cfg.num_iteration_predict,
        start_iteration=cfg.start_iteration_predict,
    )
    pred = np.asarray(pred)
    with open(cfg.output_result, "w") as fh:
        if pred.ndim == 1:
            fh.write("\n".join(f"{v:.18g}" for v in pred) + "\n")
        else:
            fh.write(
                "\n".join("\t".join(f"{v:.18g}" for v in row) for row in pred) + "\n"
            )
    log_info(f"predictions written to {cfg.output_result}")
    return 0


def _task_convert(cfg: Config) -> int:
    if not cfg.input_model:
        log_warning("task=convert_model requires input_model=<file>")
        return 1
    if cfg.convert_model_language not in ("", "cpp"):
        log_warning(f"convert_model_language={cfg.convert_model_language} unsupported (cpp only)")
        return 1
    bst = Booster(model_file=cfg.input_model)
    code = bst._gbdt.to_if_else()
    with open(cfg.convert_model, "w") as fh:  # jaxlint: disable=R12 (generated C++ SOURCE, not a loadable model artifact: nothing ever parses it back as a checkpoint, so torn-write atomicity buys nothing here)
        fh.write(code)
    log_info(f"standalone C++ predictor written to {cfg.convert_model}")
    return 0


def _task_refit(cfg: Config, params: Dict) -> int:
    if not cfg.input_model or not cfg.data:
        log_warning("task=refit requires input_model=<file> and data=<file>")
        return 1
    bst = Booster(model_file=cfg.input_model)
    loaded = load_data_file(
        cfg.data, header=cfg.header, label_column=cfg.label_column,
        weight_column=cfg.weight_column, group_column=cfg.group_column,
        ignore_column=cfg.ignore_column,
    )
    # CLI-only keys (task/data/input_model/...) must not reach refit(); the
    # refitted booster keeps the loaded model's own hyperparameters.
    new_bst = bst.refit(
        data=loaded["data"], label=loaded["label"], decay_rate=cfg.refit_decay_rate
    )
    new_bst.save_model(cfg.output_model)
    log_info(f"refitted model written to {cfg.output_model}")
    return 0


def main() -> None:
    sys.exit(run())


if __name__ == "__main__":
    main()
