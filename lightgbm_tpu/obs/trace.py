"""Lightweight span tracing (docs/OBSERVABILITY.md "Span tracing").

Round 10's registry answers "how many / how fast on aggregate"; this module
answers "WHAT was the process doing when round 412 took 3x its neighbors".
Spans are named, attributed, nesting host-side intervals:

    with trace.span("boost_round", iteration=i) as sp:
        ...
        sp.set(dispatches=3)

plus :func:`record_span` for the retroactive form — an interval whose end
the caller anchors at an **accounted sync point** it already paid for (the
windowed grower's one-round-behind async info resolve, the predict entry's
``sync_pull``).  That split embodies the zero-dispatch rule:

* opening/closing a span NEVER touches a device value.  A span close that
  performs a fresh host pull to "drain" the queue would add the blocking
  sync the round-7 protocol removed — jaxlint R10 ``sync-in-span-close``
  statically bans exactly that, the tracing twin of R9's mistiming class.
* consequently a context-manager span measures HOST-CAUSAL wall clock
  (async device work dispatched inside it may still be in flight at
  close).  Spans that must cover device time are recorded retroactively
  at the next accounted sync (``windowed_round``, ``predict.*``) — the
  instrumented layers own that anchoring, not this module.

Finished spans land in a bounded ring (cap :data:`TRACE_RING_CAP`) and
export as Chrome-trace / Perfetto-loadable JSON (:func:`to_chrome_trace`,
:func:`write_trace`; ``python -m lightgbm_tpu.obs trace`` is the CLI form,
``trace_file=`` the Config param).  Long runs overflow the ring — an
out-of-core training sweep emits far more than 8192 spans — and before
round 12 the evictions were SILENT.  Now every eviction is accounted:
with a spill sink enabled (:func:`enable_spill`; engine.train arms it
next to ``trace_file=``) evicted spans append to a bounded JSONL file
and count ``trace_spans_spilled_total``; past the byte bound, or with no
sink, they count ``trace_spans_dropped_total`` — the ring can no longer
lose history without the metrics saying so.  Spilling is pure host IO
(no device value is ever touched — the jaxlint R10 discipline holds).  The exported file keeps the raw span
records under a ``"lgbmtpu"`` key (schema :data:`SCHEMA_TRACE`) so it
round-trips through the CLI while chrome://tracing and ui.perfetto.dev
read the standard ``traceEvents`` list.

On-chip correlation: :func:`set_annotation_factory` accepts a callable
``(name, attrs) -> context manager`` entered for the body of every
context-manager span.  ``utils/profiling.py`` installs a
``jax.profiler.TraceAnnotation``/``StepTraceAnnotation`` factory when
``LGBMTPU_JAX_PROFILER=1``, lining host spans up with XLA device traces —
the jax bridge lives in that (jax-importing) layer, never here: this
module stays stdlib-only like the rest of ``lightgbm_tpu/obs``.

Enablement follows the metrics registry (``telemetry=false`` /
``LGBMTPU_TELEMETRY=0`` silences spans too); a disabled span is a cheap
no-op object.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Any, Callable, ContextManager, Dict, List, Optional

from . import metrics as _metrics

SCHEMA_TRACE = "lgbmtpu-trace-v1"
TRACE_RING_CAP = 8192

SPILL_MAX_BYTES = 64 * 1024 * 1024  # default bound for the spill sink

_lock = threading.RLock()
_ring: "collections.deque" = collections.deque(maxlen=TRACE_RING_CAP)
_ids = itertools.count(1)
_tls = threading.local()
_annotation_factory: Optional[
    Callable[[str, Dict[str, Any]], ContextManager]] = None
_spill_fh = None
_spill_path: Optional[str] = None
_spill_bytes = 0
_spill_max_bytes = SPILL_MAX_BYTES
_spill_clean = False  # previous arm in THIS process was disarmed cleanly


def enable_spill(path: str, max_bytes: int = SPILL_MAX_BYTES) -> None:
    """Arm the ring-eviction spill sink: spans evicted from the full ring
    append to ``path`` as JSONL (one raw span record per line), up to
    ``max_bytes``; beyond the bound evictions fall back to the dropped
    counter.  Appends on first arm in a process, so a watchdog-relaunched
    run keeps its pre-crash history; re-arming AFTER a clean disarm
    truncates (the previous run's complete history was sidecar + its own
    trace export — a later run's evictions must not be appended to and
    mistaken for it), as does switching to a different path mid-process."""
    global _spill_fh, _spill_path, _spill_bytes, _spill_max_bytes, _spill_clean
    with _lock:
        if _spill_fh is not None:
            try:
                _spill_fh.close()  # jaxlint: disable=L2 (rare arm/disarm path; must serialize with _handle_eviction writes, which run under this same lock by design)
            except OSError:
                pass
            # disarm BEFORE the open: if the new path fails to open, the
            # sink must read as disarmed (counted drops), not as a live
            # handle that every eviction write would find closed
            _spill_fh = None
        mode = ("w" if _spill_clean
                or (_spill_path is not None and path != _spill_path)
                else "a")
        _spill_fh = open(path, mode, encoding="utf-8")  # jaxlint: disable=L2 (rare arm path; the handle swap must be atomic vs eviction writes under the same lock)
        _spill_bytes = _spill_fh.tell()  # jaxlint: disable=L2 (rare arm path; byte-count seed is part of the atomic handle swap)
        _spill_path = path
        _spill_max_bytes = int(max_bytes)
        _spill_clean = False


def disable_spill() -> Optional[str]:
    """Close the spill sink; returns its path (None when never armed)."""
    global _spill_fh, _spill_clean
    with _lock:
        if _spill_fh is not None:
            try:
                _spill_fh.close()  # jaxlint: disable=L2 (rare disarm path; must serialize with eviction writes under the same lock)
            except OSError:
                pass
            _spill_fh = None
            _spill_clean = True
        return _spill_path


def spill_path() -> Optional[str]:
    return _spill_path


def set_ring_cap(cap: int) -> None:
    """Resize the span ring (tests; keeps the newest ``cap`` spans)."""
    global _ring
    with _lock:
        _ring = collections.deque(_ring, maxlen=max(int(cap), 1))


def _handle_eviction(evicted: Dict[str, Any]) -> None:
    """Account one span falling off the full ring — spill when armed and
    under the byte bound, count a drop otherwise.  Caller holds _lock."""
    global _spill_bytes
    if _spill_fh is not None and _spill_bytes < _spill_max_bytes:
        try:
            line = json.dumps(evicted, default=str) + "\n"
            _spill_fh.write(line)  # jaxlint: disable=L2 (spill sink design: eviction accounting is atomic with the ring mutation by construction; the write is bounded JSONL to a local file)
            _spill_bytes += len(line.encode("utf-8"))
            _metrics.counter("trace_spans_spilled_total").inc()
            return
        except (OSError, ValueError):
            pass  # unwritable sink degrades to counted drops
    _metrics.counter("trace_spans_dropped_total").inc()


def set_annotation_factory(
        fn: Optional[Callable[[str, Dict[str, Any]], ContextManager]]
) -> None:
    """Install (or clear, with None) the device-annotation mirror used by
    context-manager spans.  The factory must be cheap and must not raise;
    utils/profiling.py installs the jax.profiler one behind
    ``LGBMTPU_JAX_PROFILER=1``."""
    global _annotation_factory
    _annotation_factory = fn


def _stack() -> List["Span"]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class Span:
    """One open span.  Use via :func:`span`; ``set(**attrs)`` attaches
    attributes any time before close."""

    __slots__ = ("name", "attrs", "span_id", "parent_id", "depth",
                 "_ts", "_t0", "_annotation", "_recorded")

    def __init__(self, name: str, attrs: Dict[str, Any]) -> None:
        self.name = name
        self.attrs = attrs
        self.span_id = next(_ids)
        self.parent_id: Optional[int] = None
        self.depth = 0
        self._ts = time.time()
        self._t0 = time.perf_counter()
        self._annotation: Optional[ContextManager] = None
        self._recorded = False

    def set(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    # -- context protocol ------------------------------------------------
    def __enter__(self) -> "Span":
        st = _stack()
        if st:
            self.parent_id = st[-1].span_id
            self.depth = st[-1].depth + 1
        st.append(self)
        fac = _annotation_factory
        if fac is not None:
            try:
                self._annotation = fac(self.name, self.attrs)
                self._annotation.__enter__()
            except Exception:  # noqa: BLE001 — a broken profiler bridge
                self._annotation = None  # must never take training down
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # close = read the host clock and append to the ring.  NOTHING
        # else belongs here — in particular no device pull (jaxlint R10):
        # a span that must cover device time is recorded retroactively at
        # an accounted sync via record_span().
        dur = time.perf_counter() - self._t0
        if self._annotation is not None:
            try:
                self._annotation.__exit__(exc_type, exc, tb)
            except Exception:  # noqa: BLE001
                pass
            self._annotation = None
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        elif self in st:  # mis-nested close: drop self + anything above
            del st[st.index(self):]
        if not self._recorded:
            self._recorded = True
            if exc_type is not None:
                self.attrs.setdefault("error", exc_type.__name__)
            _append(self.name, self._ts, dur, self.attrs,
                    span_id=self.span_id, parent_id=self.parent_id,
                    depth=self.depth)
        return None


class _NoopSpan:
    """Returned while telemetry is disabled: absorbs the protocol."""

    __slots__ = ()

    def set(self, **attrs: Any) -> "_NoopSpan":
        return self

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


_NOOP = _NoopSpan()


def span(name: str, **attrs: Any):
    """Open a nesting span around a host-side section.  Records a ring
    entry on close; mirrors into the installed device-annotation factory
    (jax.profiler) when one is set."""
    if not _metrics.enabled():
        return _NOOP
    return Span(name, attrs)


def record_span(name: str, duration_s: float, **attrs: Any) -> None:
    """Record a span that ENDS NOW and lasted ``duration_s`` — the
    retroactive form for intervals anchored at an accounted sync point the
    caller just passed (async info resolve, ``sync_pull``).  Does not
    nest (no stack interaction) and never touches a device value."""
    if not _metrics.enabled():
        return
    dur = max(float(duration_s), 0.0)
    _append(name, time.time() - dur, dur, attrs)


def _append(name: str, ts: float, dur: float, attrs: Dict[str, Any],
            span_id: Optional[int] = None, parent_id: Optional[int] = None,
            depth: int = 0) -> None:
    rec = {
        "name": name,
        "ts": ts,
        "dur": dur,
        "tid": threading.get_ident(),
        "depth": depth,
        "attrs": dict(attrs),
    }
    if span_id is not None:
        rec["id"] = span_id
    if parent_id is not None:
        rec["parent"] = parent_id
    with _lock:
        if len(_ring) == _ring.maxlen:
            # the deque would evict silently — account the victim first
            _handle_eviction(_ring[0])
        _ring.append(rec)


def spans(name: Optional[str] = None) -> List[Dict[str, Any]]:
    """Finished spans currently in the ring (oldest first)."""
    with _lock:
        out = list(_ring)
    if name is not None:
        out = [s for s in out if s["name"] == name]
    return out


def reset_trace() -> None:
    """Clear the span ring (tests)."""
    with _lock:
        _ring.clear()
    _tls.stack = []


# ---------------------------------------------------------------------------
# Chrome-trace / Perfetto export
# ---------------------------------------------------------------------------

def to_chrome_trace(
        span_list: Optional[List[Dict[str, Any]]] = None) -> Dict[str, Any]:
    """Chrome Trace Event Format dict (complete "X" events, microsecond
    timestamps) that chrome://tracing and ui.perfetto.dev load directly.
    The raw span records ride along under ``"lgbmtpu"`` so the file
    round-trips through :func:`load_trace` / the obs CLI."""
    if span_list is None:
        span_list = spans()
    pid = os.getpid()
    events = []
    for s in span_list:
        ev = {
            "name": s["name"],
            "cat": "lgbmtpu",
            "ph": "X",
            "ts": s["ts"] * 1e6,
            "dur": s["dur"] * 1e6,
            "pid": pid,
            "tid": s.get("tid", 0),
            "args": s.get("attrs", {}),
        }
        events.append(ev)
    return {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "lgbmtpu": {"schema": SCHEMA_TRACE, "spans": span_list},
    }


def write_trace(path: str,
                span_list: Optional[List[Dict[str, Any]]] = None) -> int:
    """Atomically write the Chrome-trace JSON for ``span_list`` (default:
    the live ring).  Returns the number of spans written."""
    doc = to_chrome_trace(span_list)
    _metrics._atomic_write_json(path, doc)
    return len(doc["traceEvents"])


def load_trace(path: str) -> Dict[str, Any]:
    """Load + validate a trace file written by :func:`write_trace`.
    Raises ValueError on anything that is not a schema-valid trace."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    validate_trace(doc)
    return doc


def validate_trace(doc: Any) -> None:
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise ValueError("not a Chrome-trace JSON document "
                         "(missing traceEvents list)")
    meta = doc.get("lgbmtpu")
    if not isinstance(meta, dict) or meta.get("schema") != SCHEMA_TRACE:
        raise ValueError(
            f"not a {SCHEMA_TRACE} trace: lgbmtpu.schema="
            f"{meta.get('schema')!r}" if isinstance(meta, dict)
            else "missing lgbmtpu trace metadata")
    if not isinstance(meta.get("spans"), list):
        raise ValueError("lgbmtpu.spans missing or mistyped")
