"""Lightweight span tracing (docs/OBSERVABILITY.md "Span tracing").

Round 10's registry answers "how many / how fast on aggregate"; this module
answers "WHAT was the process doing when round 412 took 3x its neighbors".
Spans are named, attributed, nesting host-side intervals:

    with trace.span("boost_round", iteration=i) as sp:
        ...
        sp.set(dispatches=3)

plus :func:`record_span` for the retroactive form — an interval whose end
the caller anchors at an **accounted sync point** it already paid for (the
windowed grower's one-round-behind async info resolve, the predict entry's
``sync_pull``).  That split embodies the zero-dispatch rule:

* opening/closing a span NEVER touches a device value.  A span close that
  performs a fresh host pull to "drain" the queue would add the blocking
  sync the round-7 protocol removed — jaxlint R10 ``sync-in-span-close``
  statically bans exactly that, the tracing twin of R9's mistiming class.
* consequently a context-manager span measures HOST-CAUSAL wall clock
  (async device work dispatched inside it may still be in flight at
  close).  Spans that must cover device time are recorded retroactively
  at the next accounted sync (``windowed_round``, ``predict.*``) — the
  instrumented layers own that anchoring, not this module.

Finished spans land in a bounded ring (cap :data:`TRACE_RING_CAP`) and
export as Chrome-trace / Perfetto-loadable JSON (:func:`to_chrome_trace`,
:func:`write_trace`; ``python -m lightgbm_tpu.obs trace`` is the CLI form,
``trace_file=`` the Config param).  Long runs overflow the ring — an
out-of-core training sweep emits far more than 8192 spans — and before
round 12 the evictions were SILENT.  Now every eviction is accounted:
with a spill sink enabled (:func:`enable_spill`; engine.train arms it
next to ``trace_file=``) evicted spans append to a bounded JSONL file
and count ``trace_spans_spilled_total``; past the byte bound, or with no
sink, they count ``trace_spans_dropped_total`` — the ring can no longer
lose history without the metrics saying so.  Spilling is pure host IO
(no device value is ever touched — the jaxlint R10 discipline holds).  The exported file keeps the raw span
records under a ``"lgbmtpu"`` key (schema :data:`SCHEMA_TRACE`) so it
round-trips through the CLI while chrome://tracing and ui.perfetto.dev
read the standard ``traceEvents`` list.

On-chip correlation: :func:`set_annotation_factory` accepts a callable
``(name, attrs) -> context manager`` entered for the body of every
context-manager span.  ``utils/profiling.py`` installs a
``jax.profiler.TraceAnnotation``/``StepTraceAnnotation`` factory when
``LGBMTPU_JAX_PROFILER=1``, lining host spans up with XLA device traces —
the jax bridge lives in that (jax-importing) layer, never here: this
module stays stdlib-only like the rest of ``lightgbm_tpu/obs``.

Enablement follows the metrics registry (``telemetry=false`` /
``LGBMTPU_TELEMETRY=0`` silences spans too); a disabled span is a cheap
no-op object.

Request-scoped distributed tracing (docs/OBSERVABILITY.md "Request
tracing"): a :class:`TraceContext` — 128-bit ``trace_id``, 64-bit
``span_id``, optional parent span id, all lowercase hex — names a span's
identity EXPLICITLY so causality survives thread handoffs.  The
thread-local stack severs the moment a request crosses the serving
coalescer (submitter thread -> coalescer -> dispatcher/replica threads);
cross-thread emitters therefore pass ``parent=``/``ctx=`` to
:func:`span`/:func:`record_span` instead of inheriting the WRONG
thread's stack top, and fan-in/fan-out joins (one coalesced dispatch
serving N requests, a hedge pair racing first-result-wins) are expressed
as ``links=`` — a list of peer contexts attached to the record, the
OpenTelemetry span-link shape.  Contexts interoperate with W3C
``traceparent`` headers (:func:`parse_traceparent` /
:func:`format_traceparent`); :func:`mint_request_context` is the
/predict entry's minting point and applies the ``request_tracing=`` /
``trace_sample=`` sampling decision (an unsampled context still carries
a trace id for response correlation — its spans are simply not
recorded).  :func:`spans_for_trace` and :func:`trace_slice` are the
trace_id-indexed retrieval; :func:`merge_trace_files` folds per-rank /
per-replica trace exports into one clock-aligned flight recorder (the
launcher's events/metrics merge triad, completed).  None of this touches
a device value: ids come from ``os.urandom``, timings from host clocks
the caller already read.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import random
import threading
import time
from typing import (Any, Callable, ContextManager, Dict, Iterable, List,
                    Optional, Sequence)

from . import metrics as _metrics

SCHEMA_TRACE = "lgbmtpu-trace-v1"
TRACE_RING_CAP = 8192

# spans a single record may link to: a serving batch can coalesce many
# requests — the links list is bounded so one fan-in record cannot bloat
# the ring; overflow is counted on the record (link_overflow attr)
MAX_LINKS = 64

SPILL_MAX_BYTES = 64 * 1024 * 1024  # default bound for the spill sink

_lock = threading.RLock()
_ring: "collections.deque" = collections.deque(maxlen=TRACE_RING_CAP)
_ids = itertools.count(1)
_tls = threading.local()
_annotation_factory: Optional[
    Callable[[str, Dict[str, Any]], ContextManager]] = None
_spill_fh = None
_spill_path: Optional[str] = None
_spill_bytes = 0
_spill_max_bytes = SPILL_MAX_BYTES
_spill_clean = False  # previous arm in THIS process was disarmed cleanly


def enable_spill(path: str, max_bytes: int = SPILL_MAX_BYTES) -> None:
    """Arm the ring-eviction spill sink: spans evicted from the full ring
    append to ``path`` as JSONL (one raw span record per line), up to
    ``max_bytes``; beyond the bound evictions fall back to the dropped
    counter.  Appends on first arm in a process, so a watchdog-relaunched
    run keeps its pre-crash history; re-arming AFTER a clean disarm
    truncates (the previous run's complete history was sidecar + its own
    trace export — a later run's evictions must not be appended to and
    mistaken for it), as does switching to a different path mid-process."""
    global _spill_fh, _spill_path, _spill_bytes, _spill_max_bytes, _spill_clean
    with _lock:
        if _spill_fh is not None:
            try:
                _spill_fh.close()  # jaxlint: disable=L2 (rare arm/disarm path; must serialize with _handle_eviction writes, which run under this same lock by design)
            except OSError:
                pass
            # disarm BEFORE the open: if the new path fails to open, the
            # sink must read as disarmed (counted drops), not as a live
            # handle that every eviction write would find closed
            _spill_fh = None
        mode = ("w" if _spill_clean
                or (_spill_path is not None and path != _spill_path)
                else "a")
        _spill_fh = open(path, mode, encoding="utf-8")  # jaxlint: disable=L2 (rare arm path; the handle swap must be atomic vs eviction writes under the same lock)
        _spill_bytes = _spill_fh.tell()  # jaxlint: disable=L2 (rare arm path; byte-count seed is part of the atomic handle swap)
        _spill_path = path
        _spill_max_bytes = int(max_bytes)
        _spill_clean = False


def disable_spill() -> Optional[str]:
    """Close the spill sink; returns its path (None when never armed)."""
    global _spill_fh, _spill_clean
    with _lock:
        if _spill_fh is not None:
            try:
                _spill_fh.close()  # jaxlint: disable=L2 (rare disarm path; must serialize with eviction writes under the same lock)
            except OSError:
                pass
            _spill_fh = None
            _spill_clean = True
        return _spill_path


def spill_path() -> Optional[str]:
    return _spill_path


def set_ring_cap(cap: int) -> None:
    """Resize the span ring (tests; keeps the newest ``cap`` spans)."""
    global _ring
    with _lock:
        _ring = collections.deque(_ring, maxlen=max(int(cap), 1))


def _handle_eviction(evicted: Dict[str, Any]) -> None:
    """Account one span falling off the full ring — spill when armed and
    under the byte bound, count a drop otherwise.  Caller holds _lock."""
    global _spill_bytes
    if _spill_fh is not None and _spill_bytes < _spill_max_bytes:
        try:
            line = json.dumps(evicted, default=str) + "\n"
            _spill_fh.write(line)  # jaxlint: disable=L2 (spill sink design: eviction accounting is atomic with the ring mutation by construction; the write is bounded JSONL to a local file)
            _spill_bytes += len(line.encode("utf-8"))
            _metrics.counter("trace_spans_spilled_total").inc()
            return
        except (OSError, ValueError):
            pass  # unwritable sink degrades to counted drops
    _metrics.counter("trace_spans_dropped_total").inc()


def set_annotation_factory(
        fn: Optional[Callable[[str, Dict[str, Any]], ContextManager]]
) -> None:
    """Install (or clear, with None) the device-annotation mirror used by
    context-manager spans.  The factory must be cheap and must not raise;
    utils/profiling.py installs the jax.profiler one behind
    ``LGBMTPU_JAX_PROFILER=1``."""
    global _annotation_factory
    _annotation_factory = fn


def _stack() -> List["Span"]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


# ---------------------------------------------------------------------------
# request-scoped trace contexts (docs/OBSERVABILITY.md "Request tracing")
# ---------------------------------------------------------------------------

# request-tracing switch + sampling rate (Config request_tracing= /
# trace_sample=; configure_request_tracing applies them).  Default ON at
# rate 1.0 — the ISSUE-20 acceptance state.  The sampler is a private
# random.Random seeded from os.urandom so tests seeding the global
# random module cannot couple to the sampling stream.
_req_tracing = True
_req_sample = 1.0
_req_rng = random.Random(os.urandom(8))


def configure_request_tracing(enabled: bool = True,
                              sample: float = 1.0) -> None:
    """Apply the ``request_tracing=`` / ``trace_sample=`` Config params to
    the process (engine/serve entries call this)."""
    global _req_tracing, _req_sample
    _req_tracing = bool(enabled)
    _req_sample = min(max(float(sample), 0.0), 1.0)


def request_tracing_enabled() -> bool:
    return _req_tracing and _metrics.enabled()


def new_trace_id() -> str:
    """Fresh 128-bit trace id, 32 lowercase hex chars (W3C trace-id)."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """Fresh 64-bit span id, 16 lowercase hex chars (W3C parent-id)."""
    return os.urandom(8).hex()


class TraceContext:
    """One span's identity: ``trace_id`` (128-bit hex) names the request's
    whole causal story, ``span_id`` (64-bit hex) names THIS span inside
    it, ``parent_id`` the span it descends from (None = trace root).
    ``sampled`` carries the admission-time sampling decision: an
    unsampled context still travels (responses carry the trace id either
    way) but :func:`record_span` drops its spans."""

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled")

    def __init__(self, trace_id: str, span_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 sampled: bool = True) -> None:
        self.trace_id = trace_id
        self.span_id = span_id or new_span_id()
        self.parent_id = parent_id
        self.sampled = bool(sampled)

    def child(self) -> "TraceContext":
        """A context for a new span UNDER this one (same trace, this span
        as parent) — the cross-thread handoff shape: the enqueuing side
        makes the child, the worker thread records with ``ctx=child``."""
        return TraceContext(self.trace_id, new_span_id(), self.span_id,
                            self.sampled)

    def sibling(self) -> "TraceContext":
        """A context in the SAME trace with no parent — the fan-in shape:
        a coalesced dispatch span lives in its first request's trace and
        the member requests attach via ``links=``, not parentage."""
        return TraceContext(self.trace_id, new_span_id(), None,
                            self.sampled)

    def ref(self) -> Dict[str, str]:
        """The serialized link form stored on ring records."""
        return {"trace": self.trace_id, "sid": self.span_id}

    def __repr__(self) -> str:  # debugging/test readability only
        return (f"TraceContext({self.trace_id[:8]}…/{self.span_id}"
                f"{'' if self.sampled else ' unsampled'})")


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """Parse a W3C ``traceparent`` header (``00-<32hex>-<16hex>-<2hex>``)
    into the REMOTE caller's context (their span id, no local parent).
    Returns None on anything malformed — a bad header must never shed a
    request, it just starts a fresh trace."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) != 4:
        return None
    ver, trace_id, span_id, flags = parts
    if (len(ver) != 2 or len(trace_id) != 32 or len(span_id) != 16
            or len(flags) != 2):
        return None
    try:
        int(ver, 16), int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    if ver == "ff" or int(trace_id, 16) == 0 or int(span_id, 16) == 0:
        return None  # ff is forbidden by the spec; zero ids are invalid
    return TraceContext(trace_id, span_id, None,
                        sampled=bool(int(flags, 16) & 0x01))


def format_traceparent(ctx: TraceContext) -> str:
    """The W3C ``traceparent`` header naming ``ctx`` as the parent of
    whatever the receiver does next."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"


def mint_request_context(
        traceparent: Optional[str] = None) -> TraceContext:
    """Mint the per-request root context at an admission point (/predict,
    ``ServingRuntime.submit``).  An inbound ``traceparent`` is honored:
    the request joins the caller's trace as a child of their span.  The
    sampling decision (``request_tracing=`` x ``trace_sample=``) is made
    HERE, once per request; every downstream span inherits it."""
    sampled = (request_tracing_enabled()
               and (_req_sample >= 1.0 or _req_rng.random() < _req_sample))
    remote = parse_traceparent(traceparent)
    if remote is not None:
        return TraceContext(remote.trace_id, new_span_id(),
                            remote.span_id, sampled)
    return TraceContext(new_trace_id(), new_span_id(), None, sampled)


def current_context() -> Optional[TraceContext]:
    """The context of THIS thread's innermost open span (None outside any
    span).  This is the explicit-handoff source: read it on the enqueuing
    thread, pass ``.child()`` to the worker — never let the worker read
    its own (different) stack."""
    st = _stack()
    return st[-1].ctx if st else None


def _link_refs(links: Optional[Iterable[TraceContext]],
               attrs: Dict[str, Any]) -> Optional[List[Dict[str, str]]]:
    """Serialize a links list, bounding it at MAX_LINKS (overflow is
    recorded on the span so a truncated fan-in reads as truncated)."""
    if not links:
        return None
    refs = [c.ref() for c in links if c is not None]
    if len(refs) > MAX_LINKS:
        attrs["link_overflow"] = len(refs) - MAX_LINKS
        refs = refs[:MAX_LINKS]
    return refs or None


class Span:
    """One open span.  Use via :func:`span`; ``set(**attrs)`` attaches
    attributes any time before close.  ``ctx`` is the span's
    :class:`TraceContext` — readable after ``__enter__`` so the opener
    can hand ``sp.ctx.child()`` to another thread; ``link(ctx)`` attaches
    a span link any time before close."""

    __slots__ = ("name", "attrs", "span_id", "parent_id", "depth",
                 "ctx", "_parent_ctx", "_links",
                 "_ts", "_t0", "_annotation", "_recorded")

    def __init__(self, name: str, attrs: Dict[str, Any],
                 parent: Optional[TraceContext] = None,
                 links: Optional[Iterable[TraceContext]] = None) -> None:
        self.name = name
        self.attrs = attrs
        self.span_id = next(_ids)
        self.parent_id: Optional[int] = None
        self.depth = 0
        self._parent_ctx = parent
        self.ctx: Optional[TraceContext] = None
        self._links: List[TraceContext] = list(links) if links else []
        self._ts = time.time()
        self._t0 = time.perf_counter()
        self._annotation: Optional[ContextManager] = None
        self._recorded = False

    def set(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def link(self, ctx: Optional[TraceContext]) -> "Span":
        """Attach a span link (fan-in/fan-out peer) before close."""
        if ctx is not None:
            self._links.append(ctx)
        return self

    # -- context protocol ------------------------------------------------
    def __enter__(self) -> "Span":
        st = _stack()
        # resolve the span's identity: an EXPLICIT parent context wins —
        # the cross-thread handoff case, where this thread's stack
        # belongs to a DIFFERENT causal story and inheriting it would
        # file the span under the wrong parent (the pre-round-24 bug).
        # Else descend from this thread's innermost open span; else root
        # a fresh trace.
        if self._parent_ctx is not None:
            self.ctx = self._parent_ctx.child()
        elif st and st[-1].ctx is not None:
            self.ctx = st[-1].ctx.child()
            self.parent_id = st[-1].span_id
            self.depth = st[-1].depth + 1
        else:
            self.ctx = TraceContext(new_trace_id())
            if st:  # pre-context legacy nesting (factory-made spans)
                self.parent_id = st[-1].span_id
                self.depth = st[-1].depth + 1
        st.append(self)
        fac = _annotation_factory
        if fac is not None:
            try:
                self._annotation = fac(self.name, self.attrs)
                self._annotation.__enter__()
            except Exception:  # noqa: BLE001 — a broken profiler bridge
                self._annotation = None  # must never take training down
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # close = read the host clock and append to the ring.  NOTHING
        # else belongs here — in particular no device pull (jaxlint R10):
        # a span that must cover device time is recorded retroactively at
        # an accounted sync via record_span().
        dur = time.perf_counter() - self._t0
        if self._annotation is not None:
            try:
                self._annotation.__exit__(exc_type, exc, tb)
            except Exception:  # noqa: BLE001
                pass
            self._annotation = None
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        elif self in st:  # mis-nested close: drop self + anything above
            del st[st.index(self):]
        if not self._recorded:
            self._recorded = True
            if exc_type is not None:
                self.attrs.setdefault("error", exc_type.__name__)
            _append(self.name, self._ts, dur, self.attrs,
                    span_id=self.span_id, parent_id=self.parent_id,
                    depth=self.depth, ctx=self.ctx,
                    links=_link_refs(self._links, self.attrs))
        return None


class _NoopSpan:
    """Returned while telemetry is disabled: absorbs the protocol."""

    __slots__ = ()

    ctx: Optional[TraceContext] = None

    def set(self, **attrs: Any) -> "_NoopSpan":
        return self

    def link(self, ctx: Optional[TraceContext] = None) -> "_NoopSpan":
        return self

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


_NOOP = _NoopSpan()


def span(name: str, parent: Optional[TraceContext] = None,
         links: Optional[Iterable[TraceContext]] = None,
         **attrs: Any):
    """Open a nesting span around a host-side section.  Records a ring
    entry on close; mirrors into the installed device-annotation factory
    (jax.profiler) when one is set.  ``parent=`` names an explicit parent
    context (the cross-thread form — REQUIRED when the opener's causal
    parent lives on another thread's stack; jaxlint R21 polices the
    serve/continual thread targets); ``links=`` attaches fan-in/fan-out
    peer contexts."""
    if not _metrics.enabled():
        return _NOOP
    if parent is not None and not parent.sampled:
        return _NOOP  # the request's admission-time sampling decision
    return Span(name, attrs, parent=parent, links=links)


def record_span(name: str, duration_s: float,
                ctx: Optional[TraceContext] = None,
                parent: Optional[TraceContext] = None,
                links: Optional[Iterable[TraceContext]] = None,
                **attrs: Any) -> None:
    """Record a span that ENDS NOW and lasted ``duration_s`` — the
    retroactive form for intervals anchored at an accounted sync point the
    caller just passed (async info resolve, ``sync_pull``).  Never touches
    a device value.

    Identity is explicit, never implicit-cross-thread: ``ctx=`` records
    under a pre-minted identity (so OTHER spans could already hold links
    to it — the serving batch/leg shape); ``parent=`` derives a fresh
    child of an explicit parent context; with neither, the span adopts
    this thread's innermost open span as parent when one exists (the
    training-loop form: ``windowed_round`` under ``boost_round``) and is
    otherwise a fresh root.  ``links=`` attaches peer contexts.  A
    context carrying ``sampled=False`` drops the record — that is the
    request-sampling contract."""
    if not _metrics.enabled():
        return
    attrs = dict(attrs)
    if ctx is not None:
        rec_ctx = ctx
    elif parent is not None:
        rec_ctx = parent.child()
    else:
        cur = current_context()
        rec_ctx = cur.child() if cur is not None else None
    if rec_ctx is not None and not rec_ctx.sampled:
        return
    dur = max(float(duration_s), 0.0)
    _append(name, time.time() - dur, dur, attrs, ctx=rec_ctx,
            links=_link_refs(links, attrs))


def _append(name: str, ts: float, dur: float, attrs: Dict[str, Any],
            span_id: Optional[int] = None, parent_id: Optional[int] = None,
            depth: int = 0, ctx: Optional[TraceContext] = None,
            links: Optional[List[Dict[str, str]]] = None) -> None:
    rec = {
        "name": name,
        "ts": ts,
        "dur": dur,
        "tid": threading.get_ident(),
        "depth": depth,
        "attrs": dict(attrs),
    }
    if span_id is not None:
        rec["id"] = span_id
    if parent_id is not None:
        rec["parent"] = parent_id
    if ctx is not None:
        rec["trace"] = ctx.trace_id
        rec["sid"] = ctx.span_id
        if ctx.parent_id is not None:
            rec["psid"] = ctx.parent_id
    if links:
        rec["links"] = links
    with _lock:
        if len(_ring) == _ring.maxlen:
            # the deque would evict silently — account the victim first
            _handle_eviction(_ring[0])
        _ring.append(rec)


def spans(name: Optional[str] = None) -> List[Dict[str, Any]]:
    """Finished spans currently in the ring (oldest first)."""
    with _lock:
        out = list(_ring)
    if name is not None:
        out = [s for s in out if s["name"] == name]
    return out


def spans_for_trace(trace_id: str,
                    span_list: Optional[List[Dict[str, Any]]] = None
                    ) -> List[Dict[str, Any]]:
    """Spans recorded DIRECTLY under ``trace_id`` (oldest first) — the
    trace_id-indexed retrieval over the live ring or a loaded span list.
    For the cross-trace closure (a request's batch/leg/hedge spans that
    live in sibling traces and connect via links) use
    :func:`trace_slice`."""
    if span_list is None:
        span_list = spans()
    return [s for s in span_list if s.get("trace") == trace_id]


def trace_slice(trace_id: str,
                span_list: Optional[List[Dict[str, Any]]] = None
                ) -> List[Dict[str, Any]]:
    """The CONNECTED trace: every span reachable from ``trace_id``'s own
    spans by following links in either direction, to a fixpoint.  This is
    what reconstructs one hedged, requeued request end-to-end — the
    request span links to the winning dispatch span, the failed legs and
    the requeue/hedge records link back to the request's context — across
    threads, replicas and (after :func:`merge_trace_files`) ranks.
    Membership is by link edge or direct trace membership only; an
    adopted foreign span does NOT pull in its whole home trace."""
    if span_list is None:
        span_list = spans()
    member = [s.get("trace") == trace_id for s in span_list]
    sids = {s["sid"] for s, m in zip(span_list, member)
            if m and "sid" in s}
    changed = True
    while changed:
        changed = False
        # sids every selected span points at (links + explicit parents)
        wanted = set(sids)
        for s, m in zip(span_list, member):
            if not m:
                continue
            for ref in s.get("links", ()):
                wanted.add(ref.get("sid"))
            if "psid" in s:
                wanted.add(s["psid"])
        for i, s in enumerate(span_list):
            if member[i]:
                continue
            sid = s.get("sid")
            hit = sid is not None and sid in wanted
            if not hit:
                hit = any(ref.get("sid") in sids
                          for ref in s.get("links", ()))
            if hit:
                member[i] = True
                if sid is not None:
                    sids.add(sid)
                changed = True
    return [s for s, m in zip(span_list, member) if m]


def reset_trace() -> None:
    """Clear the span ring (tests)."""
    with _lock:
        _ring.clear()
    _tls.stack = []


# ---------------------------------------------------------------------------
# Chrome-trace / Perfetto export
# ---------------------------------------------------------------------------

def to_chrome_trace(
        span_list: Optional[List[Dict[str, Any]]] = None) -> Dict[str, Any]:
    """Chrome Trace Event Format dict (complete "X" events, microsecond
    timestamps) that chrome://tracing and ui.perfetto.dev load directly.
    The raw span records ride along under ``"lgbmtpu"`` so the file
    round-trips through :func:`load_trace` / the obs CLI."""
    if span_list is None:
        span_list = spans()
    pid = os.getpid()
    events = []
    for s in span_list:
        args = dict(s.get("attrs", {}))
        if "trace" in s:
            # surface the causal identity to Perfetto/chrome queries —
            # the raw records under "lgbmtpu" stay the machine form
            args["trace"] = s["trace"]
            args["sid"] = s.get("sid")
        ev = {
            "name": s["name"],
            "cat": "lgbmtpu",
            "ph": "X",
            "ts": s["ts"] * 1e6,
            "dur": s["dur"] * 1e6,
            "pid": s.get("pid", pid),
            "tid": s.get("tid", 0),
            "args": args,
        }
        events.append(ev)
    return {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "lgbmtpu": {"schema": SCHEMA_TRACE, "spans": span_list},
    }


def write_trace(path: str,
                span_list: Optional[List[Dict[str, Any]]] = None) -> int:
    """Atomically write the Chrome-trace JSON for ``span_list`` (default:
    the live ring).  Returns the number of spans written."""
    doc = to_chrome_trace(span_list)
    _metrics._atomic_write_json(path, doc)
    return len(doc["traceEvents"])


def load_trace(path: str) -> Dict[str, Any]:
    """Load + validate a trace file written by :func:`write_trace`.
    Raises ValueError on anything that is not a schema-valid trace."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    validate_trace(doc)
    return doc


def merge_trace_files(paths: Sequence[str],
                      out_path: Optional[str] = None) -> Dict[str, Any]:
    """Fold per-rank / per-replica trace exports into ONE clock-aligned
    Chrome-trace document — the flight recorder's merge, completing the
    launcher's events/metrics/trace triad (``python -m lightgbm_tpu.obs
    trace --merge`` is the CLI form).

    Every input is a :func:`write_trace` file.  Span ``ts`` is unix wall
    clock stamped at record time, so spans from one host (the launcher's
    worker processes) align natively; the merged timeline is the
    ts-sorted union.  Each source keeps its own Chrome ``pid`` lane
    (source index) and its spans gain a ``src`` field naming the input
    file, so a fleet-wide view separates ranks while trace ids and links
    join one request's story across them.  Missing inputs raise OSError;
    schema-invalid ones raise ValueError (a merge must never silently
    drop a rank's history).  With ``out_path`` the merged document is
    also written atomically."""
    merged: List[Dict[str, Any]] = []
    sources = []
    for idx, path in enumerate(paths):
        doc = load_trace(path)
        src = os.path.basename(str(path))
        span_list = doc["lgbmtpu"]["spans"]
        for s in span_list:
            s = dict(s)
            s["src"] = src
            s["pid"] = idx
            merged.append(s)
        ts_vals = [s["ts"] for s in span_list]
        sources.append({"src": src, "spans": len(span_list),
                        "ts_min": min(ts_vals) if ts_vals else None,
                        "ts_max": max(ts_vals) if ts_vals else None})
    merged.sort(key=lambda s: s["ts"])
    doc = to_chrome_trace(merged)
    doc["lgbmtpu"]["merged"] = {"sources": sources, "clock": "unix-wall"}
    if out_path:
        _metrics._atomic_write_json(out_path, doc)
    return doc


def validate_trace(doc: Any) -> None:
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise ValueError("not a Chrome-trace JSON document "
                         "(missing traceEvents list)")
    meta = doc.get("lgbmtpu")
    if not isinstance(meta, dict) or meta.get("schema") != SCHEMA_TRACE:
        raise ValueError(
            f"not a {SCHEMA_TRACE} trace: lgbmtpu.schema="
            f"{meta.get('schema')!r}" if isinstance(meta, dict)
            else "missing lgbmtpu trace metadata")
    if not isinstance(meta.get("spans"), list):
        raise ValueError("lgbmtpu.spans missing or mistyped")
