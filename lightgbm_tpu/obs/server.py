"""In-process HTTP metrics/health endpoint (docs/OBSERVABILITY.md).

A long-lived training or serving process needs a scrape target, not a
file dropped at exit: this module serves the live registry over a
daemon-threaded stdlib ``http.server`` (no third-party deps, no jax —
``lightgbm_tpu/obs`` stays stdlib-only).  Routes:

* ``GET /metrics``  — Prometheus text exposition of the live snapshot
  (train + serve + fault-tolerance families, per-bucket latency labels);
* ``GET /healthz``  — watchdog/degrade/nonfinite-aware status JSON.
  ``200 {"status": "ok" | "degraded"}`` or ``503 {"status":
  "unhealthy"}``; "degraded" means the process is still making progress
  on a fallback path (a Pallas kernel degraded to XLA, a fleet relaunch,
  a checkpoint fallback), "unhealthy" means data or fleet integrity
  tripped (non-finite guard, worker death, watchdog timeout, torn
  checkpoint);
* ``GET /snapshot`` — the raw JSON snapshot (schema lgbmtpu-metrics-v1);
* ``GET /events?tail=N[&kind=K]`` — the newest N ring events as NDJSON;
* ``POST /predict`` — the serving front door (JSON rows in, predictions
  out), routed through whatever ServingRuntime/ServingFleet registered
  itself via :func:`set_predict_handler`: shed -> 429, deadline -> 504,
  unhealthy/stopped -> 503 (see lightgbm_tpu/serve).

Opt-in and lifecycle: ``metrics_port=`` (Config/CLI) or
``LGBMTPU_METRICS_PORT`` starts the singleton on engine.train entry
(port 0 = ephemeral, ``server.port`` reports the bind).  The server binds
``127.0.0.1`` by default — the exposition includes operational detail
(paths, fault sites), so exposing it beyond the host is an explicit
``LGBMTPU_METRICS_HOST`` decision.  Serving happens on daemon threads, so
neither a normal exit nor the launcher's process-group kill paths can be
held open by a scrape; an atexit hook additionally closes the socket
cleanly on interpreter shutdown, and :func:`stop_server` does so on
demand.  If the requested port is already bound, the server falls back to
an ephemeral port (counted in ``metrics_server_port_fallbacks_total``)
rather than failing the training run — a telemetry endpoint must never
cost the caller a model.
"""

from __future__ import annotations

import atexit
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

import os

from . import metrics as _metrics

DEFAULT_HOST = "127.0.0.1"

# (counter, problem description) tables driving /healthz.  Severity is the
# counter's meaning, not its size: one non-finite round is already a data
# integrity failure, one degrade flip is already a permanent fallback.
UNHEALTHY_COUNTERS = (
    ("train_nonfinite_errors_total", "non-finite gradients/hessians/stats"),
    ("launcher_worker_deaths_total", "launcher worker died"),
    ("launcher_timeouts_total", "launcher watchdog timeout"),
    ("checkpoint_torn_total", "torn checkpoint detected"),
    ("fleet_hangs_total", "hung rank detected by the heartbeat watchdog"),
)
DEGRADED_COUNTERS = (
    ("degrade_disabled_total", "Pallas kernel degraded to XLA fallback"),
    ("launcher_relaunches_total", "fleet relaunched after a failure"),
    ("train_windowed_retries_total", "windowed W-bound prediction retries"),
    ("checkpoint_fallbacks_total", "resume fell back to an older snapshot"),
    ("fleet_resumes_total", "fleet resumed from a checkpoint round"),
    ("faults_injected_total", "injected faults fired (test harness armed)"),
    ("continual_update_failures_total",
     "continual update failed; serving continues on the previous ensemble"),
    ("lock_order_violations_total",
     "lock-order inversion witnessed by the runtime lock sanitizer"),
)
# gauge-driven degraded states: unlike the cumulative counters above these
# are CURRENT conditions — the serving runtime sets serve_shedding to 1
# while it refuses submissions (queue bound / tenant quota / p99 SLO /
# unhealthy process, lightgbm_tpu/serve) and back to 0 when admissions
# resume, so /healthz flips degraded exactly for the shedding interval
DEGRADED_GAUGES = (
    ("serve_shedding", "serving runtime is shedding load (Overloaded)"),
    # armed by the continual runner's staleness_slo_s: the serving
    # ensemble has un-incorporated ingest older than the SLO — stale
    # predictions, still correct ones (lightgbm_tpu/continual)
    ("continual_staleness_exceeded",
     "serving model is stale past the continual staleness SLO"),
    # set by the serving fleet (lightgbm_tpu/serve/fleet.py) while ANY
    # replica is not in active rotation (ejected / half-open / dead /
    # restarting) — requests still serve on the healthy replicas, so
    # this is degradation, not unavailability
    ("serve_fleet_degraded",
     "serving fleet has replicas out of rotation"),
)

# ---------------------------------------------------------------------------
# serve-layer hooks: obs stays stdlib-only (no jax, no serve import), so the
# serving runtime REGISTERS callables here instead of being imported —
# /predict routes through the hook, /healthz merges the replica table
# ---------------------------------------------------------------------------

_predict_fn: Optional[Callable[..., Tuple]] = None
_health_extra_fn: Optional[Callable[[], Dict[str, Any]]] = None


def set_predict_handler(fn: Callable[..., Tuple]) -> None:
    """Attach the process's ``POST /predict`` handler.  The current
    contract is ``fn(payload, traceparent=None) -> (http_status,
    body_dict, traceparent_out)`` — the inbound W3C header (or None)
    goes in, the outbound header (or None) comes back and is emitted on
    the response.  A legacy 2-tuple handler ``fn(payload) -> (status,
    body)`` still works (no trace header either way).  Last registration
    wins — one process, one front door."""
    global _predict_fn
    _predict_fn = fn


def clear_predict_handler(fn) -> None:
    """Detach ``fn`` if it is the current handler (a stopped runtime must
    not unregister its successor's route)."""
    global _predict_fn
    if _predict_fn == fn:
        _predict_fn = None


def set_health_extra(fn: Callable[[], Dict[str, Any]]) -> None:
    """Attach a callable whose dict is merged into the /healthz body under
    ``"serve_fleet"`` — the replica state table."""
    global _health_extra_fn
    _health_extra_fn = fn


def clear_health_extra(fn) -> None:
    global _health_extra_fn
    if _health_extra_fn == fn:
        _health_extra_fn = None


def health(snap: Optional[Dict[str, Any]] = None) -> Tuple[int, Dict[str, Any]]:
    """(http_status, body) for /healthz, derived from the snapshot's
    counters (live registry when ``snap`` is None).  Pure host-side reads
    — the health probe adds zero device work, like everything in obs."""
    if snap is None:
        snap = _metrics.snapshot()
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    problems: List[Dict[str, Any]] = []
    status = "ok"
    for table, severity in ((UNHEALTHY_COUNTERS, "unhealthy"),
                            (DEGRADED_COUNTERS, "degraded")):
        for name, why in table:
            # labeled variants count against the base family too
            n = sum(int(v) for cn, v in counters.items()
                    if _metrics._split_labels(cn)[0] == name)
            if n > 0:
                problems.append({"counter": name, "count": n, "why": why,
                                 "severity": severity})
                if severity == "unhealthy":
                    status = "unhealthy"
                elif status == "ok":
                    status = "degraded"
    shedding = False
    for name, why in DEGRADED_GAUGES:
        v = float(gauges.get(name, 0.0))
        if v:
            problems.append({"gauge": name, "value": v, "why": why,
                             "severity": "degraded"})
            if status == "ok":
                status = "degraded"
            if name == "serve_shedding":
                shedding = True
    body = {
        "status": status,
        "problems": problems,
        "shedding": shedding,
        "telemetry_enabled": bool(snap.get("enabled", True)),
        "rank": snap.get("rank"),
        "ts": snap.get("ts"),
    }
    extra = _health_extra_fn
    if extra is not None:
        try:
            body["serve_fleet"] = extra()
        except Exception:  # noqa: BLE001 — a health probe must not 500
            body["serve_fleet"] = {"error": "replica table unavailable"}
    return (503 if status == "unhealthy" else 200), body


def _make_handler(server: "MetricsServer"):
    class Handler(BaseHTTPRequestHandler):
        server_version = "lgbmtpu-obs"
        protocol_version = "HTTP/1.1"

        def log_message(self, *args) -> None:  # noqa: D102, ARG002
            pass  # a scrape every few seconds must not spam the run log

        def _send(self, code: int, body: bytes, ctype: str,
                  headers: Optional[Dict[str, str]] = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
            try:
                url = urlparse(self.path)
                route = url.path.rstrip("/") or "/"
                if route == "/metrics":
                    text = _metrics.render_prometheus(server.snapshot_fn())
                    self._send(200, text.encode("utf-8"),
                               "text/plain; version=0.0.4; charset=utf-8")
                elif route == "/healthz":
                    code, body = server.health_fn()
                    self._send(code, (json.dumps(body, default=str) + "\n")
                               .encode("utf-8"), "application/json")
                elif route == "/snapshot":
                    self._send(200, (json.dumps(server.snapshot_fn(),
                                                indent=1, default=str) + "\n")
                               .encode("utf-8"), "application/json")
                elif route == "/events":
                    q = parse_qs(url.query)
                    try:
                        tail = int(q.get("tail", ["100"])[0])
                    except ValueError:
                        tail = 100
                    kind = q.get("kind", [None])[0]
                    evs = server.events_fn(kind)
                    if tail >= 0:
                        evs = evs[-tail:]
                    body = "".join(json.dumps(e, default=str) + "\n"
                                   for e in evs)
                    self._send(200, body.encode("utf-8"),
                               "application/x-ndjson")
                elif route == "/predict":
                    self._send(405, b'{"error": "use POST /predict"}\n',
                               "application/json")
                else:
                    self._send(404, b"not found\n", "text/plain")
            except BrokenPipeError:
                pass  # the scraper hung up mid-response
            except Exception as e:  # noqa: BLE001 — endpoint must not die
                try:
                    self._send(500, f"error: {e}\n".encode("utf-8"),
                               "text/plain")
                except OSError:
                    pass

        def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
            try:
                route = urlparse(self.path).path.rstrip("/") or "/"
                if route != "/predict":
                    self._send(404, b"not found\n", "text/plain")
                    return
                fn = _predict_fn
                if fn is None:
                    self._send(503, b'{"error": "unavailable", "detail": '
                                    b'"no serving runtime attached"}\n',
                               "application/json")
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0) or 0)
                except ValueError:
                    n = 0
                if n > 32 << 20:
                    self._send(413, b'{"error": "payload too large"}\n',
                               "application/json")
                    return
                try:
                    payload = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._send(400, b'{"error": "bad_request", "detail": '
                                    b'"body is not valid JSON"}\n',
                               "application/json")
                    return
                # distributed tracing (docs/OBSERVABILITY.md "Request
                # tracing"): the inbound W3C traceparent (if any) is
                # handed to the runtime, which mints the request context
                # from it; the response ALWAYS names the request's trace
                # — body trace_id + outbound traceparent header — so a
                # caller can join its own trace to the flight recorder.
                tp_in = self.headers.get("traceparent")
                try:
                    code, body, tp_out = fn(payload, traceparent=tp_in)
                except TypeError:
                    # a legacy 1-arg handler (tests / external hooks)
                    code, body = fn(payload)
                    tp_out = None
                self._send(code, (json.dumps(body, default=str) + "\n")
                           .encode("utf-8"), "application/json",
                           headers={"traceparent": tp_out} if tp_out
                           else None)
            except BrokenPipeError:
                pass  # the client hung up mid-response
            except Exception as e:  # noqa: BLE001 — endpoint must not die
                try:
                    self._send(500, f"error: {e}\n".encode("utf-8"),
                               "text/plain")
                except OSError:
                    pass

    return Handler


class MetricsServer:
    """One HTTP endpoint.  ``port=0`` binds an ephemeral port; a busy
    explicit port falls back to ephemeral (``fell_back``) instead of
    raising.  The provider callables default to the live registry —
    ``python -m lightgbm_tpu.obs serve`` swaps in a saved snapshot."""

    def __init__(self, port: int = 0, host: str = DEFAULT_HOST, *,
                 snapshot_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 events_fn: Optional[Callable[[Optional[str]], List]] = None,
                 health_fn: Optional[Callable[[], Tuple[int, Dict]]] = None):
        self.requested_port = int(port)
        self.host = host
        self.snapshot_fn = snapshot_fn or _metrics.snapshot
        self.events_fn = events_fn or (lambda kind=None: _metrics.events(kind))
        self.health_fn = health_fn or (lambda: health(self.snapshot_fn()))
        self.port: Optional[int] = None
        self.fell_back = False
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def running(self) -> bool:
        return self._httpd is not None

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        handler = _make_handler(self)
        try:
            self._httpd = ThreadingHTTPServer(
                (self.host, self.requested_port), handler)
        except OSError:
            if self.requested_port == 0:
                raise
            # port-in-use fallback: an ephemeral endpoint beats none, and
            # a telemetry bind conflict must never fail the training run
            self._httpd = ThreadingHTTPServer((self.host, 0), handler)
            self.fell_back = True
            _metrics.counter("metrics_server_port_fallbacks_total").inc()
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="lgbmtpu-metrics-server")
        self._thread.start()
        _metrics.event("metrics_server_start", port=self.port,
                       host=self.host, fallback=self.fell_back,
                       requested_port=self.requested_port)
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        thread, self._thread = self._thread, None
        if httpd is None:
            return
        try:
            httpd.shutdown()
            httpd.server_close()
        except OSError:
            pass
        if thread is not None:
            thread.join(timeout=5)
        _metrics.event("metrics_server_stop", port=self.port)

    def url(self, route: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{route}"


# ---------------------------------------------------------------------------
# process singleton (engine.train / long-lived serving processes)
# ---------------------------------------------------------------------------

_singleton_lock = threading.Lock()
_singleton: Optional[MetricsServer] = None
_atexit_armed = False


def start_server(port: int = 0, host: Optional[str] = None) -> MetricsServer:
    """Start (or return) the process-wide endpoint.  Idempotent: a second
    call returns the running server regardless of the requested port — one
    process, one endpoint."""
    global _singleton, _atexit_armed
    with _singleton_lock:
        if _singleton is not None and _singleton.running:
            return _singleton
        srv = MetricsServer(
            port=port,
            host=host or os.environ.get("LGBMTPU_METRICS_HOST", DEFAULT_HOST))
        srv.start()
        _singleton = srv
        if not _atexit_armed:
            _atexit_armed = True
            atexit.register(stop_server)
        return srv


def stop_server() -> None:
    """Stop the process-wide endpoint (idempotent; also the atexit hook,
    so engine exit and interpreter shutdown close the socket cleanly)."""
    global _singleton
    with _singleton_lock:
        srv, _singleton = _singleton, None
    if srv is not None:
        srv.stop()


def get_server() -> Optional[MetricsServer]:
    return _singleton if (_singleton is not None and _singleton.running) \
        else None


def maybe_start(port: Optional[int] = None) -> Optional[MetricsServer]:
    """The Config/env opt-in gate: ``port`` is the explicit
    ``metrics_port=`` value (None = unset, falls through to
    ``LGBMTPU_METRICS_PORT``); negative or unresolvable means off.
    Telemetry disabled means off too — a metrics endpoint over a frozen
    registry would report lies."""
    if not _metrics.enabled():
        return None
    if port is None:
        raw = os.environ.get("LGBMTPU_METRICS_PORT")
        if raw is None:
            return None
        try:
            port = int(raw)
        except ValueError:
            return None
    if port < 0:
        return None
    return start_server(port)
