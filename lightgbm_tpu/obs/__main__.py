"""CLI: ``python -m lightgbm_tpu.obs [COMMAND] ...``.

Default (no subcommand, the round-10 form): render a metrics snapshot —
``python -m lightgbm_tpu.obs [snapshot.json] [--format prometheus|
lightgbm|json]``.  With a path it renders a snapshot previously written
via ``metrics_file=`` or :func:`lightgbm_tpu.obs.write_snapshot`; with no
path it dumps the live in-process registry (empty in a fresh interpreter —
the path form is the operational one).  A schema-invalid snapshot exits 2
WITHOUT emitting a partial report: the render is fully materialized
before anything is printed.

Subcommands:

* ``trace [trace.json ...] [--merge] [--trace-id HEX32] [-o OUT]`` —
  export spans as Chrome-trace/Perfetto JSON.  With a path, validates +
  re-emits a saved trace file (``trace_file=`` / :func:`write_trace`);
  without, exports the live span ring.  ``--merge`` folds several
  per-rank/per-replica trace files into one clock-aligned timeline (the
  flight recorder; completes the launcher's events/metrics merge triad);
  ``--trace-id`` narrows the export to one request's connected trace
  (span-link closure — the hedged/requeued story end-to-end).  ``-o``
  writes atomically instead of printing.
* ``serve SNAPSHOT [--port N] [--host H]`` — standalone HTTP endpoint
  over a saved snapshot file (``/metrics``, ``/healthz``, ``/snapshot``;
  ``/events`` serves a sibling ``--events`` JSONL when given) — the
  post-mortem twin of the in-process ``metrics_port=`` endpoint.
* ``tail EVENTS.jsonl [-n N] [--kind K] [--follow]`` — print the newest N
  structured events (one JSON object per line); ``--follow`` keeps
  following appends like ``tail -f``.

Exit codes: 0 ok, 2 on missing/invalid inputs.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from .metrics import (load_snapshot, render_lightgbm, render_prometheus,
                      snapshot)
from . import trace as _trace


def _cmd_dump(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.obs",
        description="dump a lightgbm_tpu metrics snapshot")
    parser.add_argument("path", nargs="?", default=None,
                        help="snapshot JSON written by metrics_file= / "
                             "write_snapshot (default: the live registry)")
    parser.add_argument("--format", choices=("prometheus", "lightgbm",
                                             "json"),
                        default="prometheus")
    args = parser.parse_args(argv)

    if args.path is not None:
        try:
            snap = load_snapshot(args.path)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    else:
        snap = snapshot()

    # materialize the FULL report before printing any of it: a malformed
    # snapshot must exit non-zero with zero partial output, never die
    # halfway through a report a script is already parsing
    try:
        if args.format == "json":
            out = json.dumps(snap, indent=1, default=str) + "\n"
        elif args.format == "lightgbm":
            out = "".join(line + "\n" for line in render_lightgbm(snap))
        else:
            out = render_prometheus(snap)
    except Exception as e:  # noqa: BLE001 — any render failure is exit 2
        print(f"error: snapshot does not render ({e})", file=sys.stderr)
        return 2
    sys.stdout.write(out)
    return 0


def _cmd_trace(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.obs trace",
        description="export spans as Chrome-trace/Perfetto JSON")
    parser.add_argument("paths", nargs="*", default=[],
                        help="saved trace file(s) (trace_file= / "
                             "write_trace) to validate + re-emit "
                             "(default: export the live span ring); "
                             "several paths require --merge")
    parser.add_argument("--merge", action="store_true",
                        help="fold the given per-rank/per-replica trace "
                             "files into ONE clock-aligned timeline "
                             "(each source keeps its own pid lane; the "
                             "launcher's events/metrics merge triad, "
                             "completed)")
    parser.add_argument("--trace-id", default=None, metavar="HEX32",
                        help="narrow the export to one request's "
                             "CONNECTED trace: its own spans plus "
                             "everything reachable over span links "
                             "(coalesced batches, failed legs, "
                             "hedge/requeue records)")
    parser.add_argument("-o", "--output", default=None,
                        help="write the trace JSON here (atomic) instead "
                             "of printing it")
    args = parser.parse_args(argv)
    if len(args.paths) > 1 and not args.merge:
        print("error: multiple trace files need --merge", file=sys.stderr)
        return 2
    try:
        if args.merge:
            if not args.paths:
                print("error: --merge needs at least one trace file",
                      file=sys.stderr)
                return 2
            doc = _trace.merge_trace_files(args.paths)
        elif args.paths:
            doc = _trace.load_trace(args.paths[0])
        else:
            doc = _trace.to_chrome_trace()
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.trace_id:
        meta = doc.get("lgbmtpu", {})
        sliced = _trace.trace_slice(args.trace_id.strip().lower(),
                                    meta.get("spans", []))
        doc = _trace.to_chrome_trace(sliced)
        if "merged" in meta:  # keep the provenance of a merged input
            doc["lgbmtpu"]["merged"] = meta["merged"]
    if args.output:
        from .metrics import _atomic_write_json

        try:
            _atomic_write_json(args.output, doc)
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(f"wrote {len(doc['traceEvents'])} span(s) to {args.output}")
    else:
        print(json.dumps(doc, indent=1, default=str))
    return 0


def _cmd_serve(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.obs serve",
        description="standalone HTTP endpoint over a saved snapshot")
    parser.add_argument("path", help="snapshot JSON (metrics_file=)")
    parser.add_argument("--port", type=int, default=0,
                        help="bind port (default: ephemeral)")
    parser.add_argument("--host", default=None,
                        help="bind host (default 127.0.0.1 — the "
                             "exposition includes operational detail)")
    parser.add_argument("--events", default=None,
                        help="optional events JSONL served at /events")
    args = parser.parse_args(argv)
    try:
        srv = serve_snapshot(args.path, port=args.port, host=args.host,
                             events_path=args.events)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(f"serving {args.path} at {srv.url('/metrics')} "
          f"(/healthz, /snapshot, /events) — Ctrl-C to stop", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


def serve_snapshot(path: str, port: int = 0, host=None, events_path=None):
    """Build + start a MetricsServer over a saved snapshot file (the CLI
    ``serve`` body, importable so tests and tools can drive it without a
    blocking foreground loop).  Raises OSError/ValueError on a missing or
    schema-invalid snapshot."""
    from .server import DEFAULT_HOST, MetricsServer, health

    snap = load_snapshot(path)  # validates; raise before binding anything
    events = []
    if events_path:
        with open(events_path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail from a crashed worker
                if isinstance(rec, dict):
                    events.append(rec)
    return MetricsServer(
        port=port, host=host or DEFAULT_HOST,
        snapshot_fn=lambda: snap,
        events_fn=lambda kind=None: (
            [e for e in events if e.get("kind") == kind] if kind else events),
        health_fn=lambda: health(snap),
    ).start()


def _cmd_tail(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.obs tail",
        description="print (and optionally follow) a structured events "
                    "JSONL stream")
    parser.add_argument("path", help="events JSONL (LGBMTPU_EVENTS_FILE / "
                                     "fleet_events.jsonl)")
    parser.add_argument("-n", "--lines", type=int, default=10)
    parser.add_argument("--kind", default=None,
                        help="only events of this kind")
    parser.add_argument("--follow", action="store_true",
                        help="keep following appended records (tail -f)")
    parser.add_argument("--poll", type=float, default=0.5,
                        help="follow poll interval seconds")
    args = parser.parse_args(argv)

    def matches(line: str):
        line = line.strip()
        if not line:
            return None
        try:
            rec = json.loads(line)
        except ValueError:
            return None  # torn tail — skip, never die
        if not isinstance(rec, dict):
            return None
        if args.kind is not None and rec.get("kind") != args.kind:
            return None
        return rec

    try:
        fh = open(args.path, encoding="utf-8")
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    with fh:
        recs = [r for r in (matches(line) for line in fh) if r is not None]
        # -n 0 is the `tail -n 0 -f` idiom: print NO history (a negated
        # zero slice would dump the whole file)
        for rec in (recs[-args.lines:] if args.lines > 0 else []):
            print(json.dumps(rec, default=str), flush=True)
        if not args.follow:
            return 0
        try:
            while True:
                line = fh.readline()
                if not line:
                    time.sleep(max(args.poll, 0.05))
                    continue
                rec = matches(line)
                if rec is not None:
                    print(json.dumps(rec, default=str), flush=True)
        except KeyboardInterrupt:
            return 0


_COMMANDS = {"trace": _cmd_trace, "serve": _cmd_serve, "tail": _cmd_tail}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in _COMMANDS:
        return _COMMANDS[argv[0]](argv[1:])
    if argv and argv[0] == "dump":  # explicit spelling of the default
        argv = argv[1:]
    return _cmd_dump(argv)


if __name__ == "__main__":
    sys.exit(main())
