"""CLI: ``python -m lightgbm_tpu.obs [snapshot.json] [--format ...]``.

With a path, renders a snapshot previously written via ``metrics_file=``
(Config/CLI param) or :func:`lightgbm_tpu.obs.write_snapshot`; with no
path, dumps the live in-process registry (empty in a fresh interpreter —
the path form is the operational one).  Formats: ``prometheus`` (default),
``lightgbm`` (reference "Time for X" report lines), ``json``.
"""

from __future__ import annotations

import argparse
import json
import sys

from .metrics import (load_snapshot, render_lightgbm, render_prometheus,
                      snapshot)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.obs",
        description="dump a lightgbm_tpu metrics snapshot")
    parser.add_argument("path", nargs="?", default=None,
                        help="snapshot JSON written by metrics_file= / "
                             "write_snapshot (default: the live registry)")
    parser.add_argument("--format", choices=("prometheus", "lightgbm",
                                             "json"),
                        default="prometheus")
    args = parser.parse_args(argv)

    if args.path is not None:
        try:
            snap = load_snapshot(args.path)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    else:
        snap = snapshot()

    if args.format == "json":
        print(json.dumps(snap, indent=1, default=str))
    elif args.format == "lightgbm":
        for line in render_lightgbm(snap):
            print(line)
    else:
        sys.stdout.write(render_prometheus(snap))
    return 0


if __name__ == "__main__":
    sys.exit(main())
