"""Runtime observability: metrics registry, event sink, span tracing,
HTTP endpoint, run reports.

See docs/OBSERVABILITY.md for the metric catalog, the event schema, the
span-tracing semantics, and the zero-dispatch rule this subsystem is built
around.  ``python -m lightgbm_tpu.obs`` dumps the live registry (or a
saved snapshot file) as Prometheus text exposition; subcommands ``trace``
(Chrome-trace export), ``serve`` (standalone HTTP endpoint over a
snapshot), and ``tail`` (follow an events JSONL) cover the operational
loops.  Everything in this package is stdlib-only — it never imports jax.
"""

from .metrics import (  # noqa: F401
    FLEET_SCHEMA, REGISTRY, RESERVOIR_CAP, SCHEMA, SECTION_PREFIX, Counter,
    Gauge, Histogram, Registry, clear_prefix, counter, enabled, event,
    events, gauge, histogram, histogram_items, labeled, load_fleet_metrics,
    load_snapshot, merge_event_files, merge_snapshot_files,
    register_collector, render_lightgbm, render_prometheus,
    render_prometheus_fleet, reset, set_enabled, set_events_file, snapshot,
    start_periodic_snapshots, stop_periodic_snapshots,
    validate_fleet_metrics, validate_snapshot, write_snapshot,
)
from .server import (  # noqa: F401
    MetricsServer, get_server, health, maybe_start, start_server,
    stop_server,
)
from .trace import (  # noqa: F401
    SCHEMA_TRACE, TRACE_RING_CAP, Span, load_trace, record_span,
    reset_trace, set_annotation_factory, span, spans, to_chrome_trace,
    validate_trace, write_trace,
)

__all__ = [
    "FLEET_SCHEMA", "REGISTRY", "RESERVOIR_CAP", "SCHEMA", "SCHEMA_TRACE",
    "SECTION_PREFIX", "TRACE_RING_CAP", "Counter", "Gauge", "Histogram",
    "MetricsServer", "Registry", "Span", "clear_prefix", "counter",
    "enabled", "event", "events", "gauge", "get_server", "health",
    "histogram", "histogram_items", "labeled", "load_fleet_metrics",
    "load_snapshot", "load_trace", "maybe_start", "merge_event_files",
    "merge_snapshot_files", "record_span", "register_collector",
    "render_lightgbm", "render_prometheus", "render_prometheus_fleet",
    "reset", "reset_trace", "set_annotation_factory", "set_enabled",
    "set_events_file", "snapshot", "span", "spans",
    "start_periodic_snapshots", "start_server", "stop_periodic_snapshots",
    "stop_server", "to_chrome_trace", "validate_fleet_metrics",
    "validate_snapshot", "validate_trace", "write_snapshot", "write_trace",
]
