"""Runtime observability: metrics registry, event sink, run reports.

See docs/OBSERVABILITY.md for the metric catalog, the event schema, and the
zero-dispatch rule this subsystem is built around.  ``python -m
lightgbm_tpu.obs`` dumps the live registry (or a saved snapshot file) as
Prometheus text exposition.
"""

from .metrics import (  # noqa: F401
    REGISTRY, RESERVOIR_CAP, SCHEMA, SECTION_PREFIX, Counter, Gauge,
    Histogram, Registry, clear_prefix, counter, enabled, event, events,
    gauge, histogram, histogram_items, load_snapshot, merge_event_files,
    register_collector, render_lightgbm, render_prometheus, reset,
    set_enabled, set_events_file, snapshot, validate_snapshot,
    write_snapshot,
)

__all__ = [
    "REGISTRY", "RESERVOIR_CAP", "SCHEMA", "SECTION_PREFIX", "Counter",
    "Gauge", "Histogram", "Registry", "clear_prefix", "counter", "enabled",
    "event", "events", "gauge", "histogram", "histogram_items",
    "load_snapshot", "merge_event_files", "register_collector",
    "render_lightgbm", "render_prometheus", "reset", "set_enabled",
    "set_events_file", "snapshot", "validate_snapshot", "write_snapshot",
]
