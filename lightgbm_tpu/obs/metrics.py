"""Process-wide metrics registry + structured event sink (docs/OBSERVABILITY.md).

The reference ships TIMETAG per-phase timers and "Time for X: Y s" summaries
(SURVEY §6.1/§6.2); a production serving/training system additionally needs
counters, latency percentiles, and machine-readable run artifacts.  This
module is that layer, with one hard design rule inherited from the round-7/8
budget protocol:

**Telemetry adds ZERO device dispatches and ZERO blocking syncs.**  Nothing
in this module imports jax or touches a device value.  Every device-derived
metric is recorded by a caller that already holds the value on the host —
the windowed grower's one-round-behind async info vector, the accounted
``sync_pull`` at a predict entry, the sanitizer's ``jax.monitoring``
listener — so enabling telemetry (it is default-on) cannot change the
dispatch/sync budgets that ``tests/test_retrace.py`` and
``tests/test_predict_budget.py`` pin.

Three primitives plus an event stream:

* :class:`Counter` — monotonic ``inc(n)``;
* :class:`Gauge` — last-write-wins ``set(v)``;
* :class:`Histogram` — bounded reservoir (cap 512, deterministic
  per-name-seeded sampling) with exact ``count``/``sum``/``min``/``max``
  and reservoir-estimated percentiles (p50/p90/p99);
* :func:`event` — a structured record appended to an in-memory ring
  (cap 4096) and, when a sink file is configured
  (``LGBMTPU_EVENTS_FILE`` env or :func:`set_events_file`), to a JSONL
  file — one JSON object per line, schema below.

Event schema (every record)::

    {"ts": <unix float>, "kind": <str>, "rank": <int|None>, ...fields}

``rank`` is read from ``LIGHTGBM_TPU_RANK`` so launcher workers stamp their
own records; ``parallel/launcher.py`` aggregates per-rank files into one
fleet-level JSONL.

Collectors bridge subsystems that keep their own authoritative counters
(``utils/sanitizer.py``'s dispatch/sync/compile ledger): a registered
collector is called at :func:`snapshot` time and its values merge into the
snapshot — zero per-event overhead, one read per snapshot.

Snapshots are plain JSON (schema ``lgbmtpu-metrics-v1``); render them as
Prometheus text exposition (:func:`render_prometheus`) or reference-style
log lines (:func:`render_lightgbm`), or via ``python -m lightgbm_tpu.obs``.

Kept import-light (stdlib only) on purpose: utils/faults.py, the launcher's
thin worker processes, and checkpoint writers all record here without
paying a jax import.
"""

from __future__ import annotations

import collections
import json
import os
import random
import re
import tempfile
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional

SCHEMA = "lgbmtpu-metrics-v1"
RESERVOIR_CAP = 512
EVENT_RING_CAP = 4096
# exemplar freshness window: the kept witness outlier yields to ANY newer
# exemplar once it is this old, so a single cold-start spike cannot pin
# the series' exemplar forever
EXEMPLAR_TTL_S = 60.0
_PROM_PREFIX = "lgbmtpu_"

_lock = threading.RLock()
# dedicated event-sink IO leaf lock: the JSONL write/flush of an event
# record happens here, NOT under the registry ``_lock`` every counter
# inc contends on — a slow disk must never stall the hot metric paths
# (the L2 lock-lint finding this split fixed).  Order: never taken while
# holding ``_lock`` (both call sites release the registry lock first);
# the write-error path nests ``_lock`` INSIDE it, which is the one
# allowed direction.
_events_io_lock = threading.Lock()
# the process default (env-derived); Config application restores it for
# models that do not set telemetry= explicitly, so one model's
# telemetry=false cannot silently disable a later model's metrics_file=
DEFAULT_ENABLED: bool = os.environ.get("LGBMTPU_TELEMETRY", "1") != "0"
_enabled: bool = DEFAULT_ENABLED


def set_enabled(on: bool) -> None:
    """Process-wide switch (``telemetry=false`` Config param routes here).
    Disabling makes every record call a cheap no-op; existing values stay
    readable."""
    global _enabled
    with _lock:
        _enabled = bool(on)


def enabled() -> bool:
    return _enabled


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if not _enabled:
            return
        with _lock:
            self._value += n

    @property
    def value(self) -> int:
        with _lock:
            return self._value


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0.0

    def set(self, v: float) -> None:
        if not _enabled:
            return
        with _lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with _lock:
            return self._value


class Histogram:
    """Bounded-reservoir distribution: exact count/sum/min/max, percentiles
    estimated from a RESERVOIR_CAP-sample reservoir (classic algorithm-R,
    seeded per name so runs are reproducible)."""

    __slots__ = ("name", "count", "total", "min", "max", "_samples", "_rng",
                 "_exemplar")

    def __init__(self, name: str) -> None:
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._samples: List[float] = []
        # stable per-name seed (str hash() is salted per process — crc32
        # keeps the "identical runs keep identical reservoirs" promise)
        self._rng = random.Random(zlib.crc32(name.encode("utf-8")))
        # OpenMetrics-style exemplar: the trace id of a WITNESS outlier —
        # {"trace_id", "value", "ts"} — so a latency series answers
        # "show me one request that actually looked like this tail"
        self._exemplar: Optional[Dict[str, Any]] = None

    def observe(self, v: float, always: bool = False,
                exemplar: Optional[str] = None) -> None:
        """``always=True`` records even while telemetry is disabled — for
        explicitly invoked profiling APIs (utils/profiling.py
        timed_section), where the call itself is the opt-in.
        ``exemplar=`` attaches a trace id witnessing this observation;
        the histogram keeps the witness of the LARGEST value seen in the
        trailing EXEMPLAR_TTL_S window (outliers win, a one-off spike
        ages out)."""
        if not (_enabled or always):
            return
        v = float(v)
        with _lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if len(self._samples) < RESERVOIR_CAP:
                self._samples.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < RESERVOIR_CAP:
                    self._samples[j] = v
            if exemplar is not None:
                ex = self._exemplar
                now = time.time()
                if (ex is None or v >= ex["value"]
                        or now - ex["ts"] > EXEMPLAR_TTL_S):
                    self._exemplar = {"trace_id": str(exemplar),
                                      "value": v, "ts": now}

    @property
    def exemplar(self) -> Optional[Dict[str, Any]]:
        with _lock:
            return dict(self._exemplar) if self._exemplar else None

    def percentile(self, p: float) -> Optional[float]:
        with _lock:
            s = sorted(self._samples)
        return _percentile_of(s, p)

    def summary(self, include_samples: bool = False) -> Dict[str, Any]:
        """``include_samples=True`` attaches the raw reservoir — the form
        per-rank snapshot files carry so the launcher's fleet merge can
        recompute exact combined percentiles instead of averaging
        per-rank estimates."""
        with _lock:
            n, tot, lo, hi = self.count, self.total, self.min, self.max
            samples = list(self._samples) if include_samples else None
        out = {
            "count": n, "sum": tot, "min": lo, "max": hi,
            "p50": self.percentile(50), "p90": self.percentile(90),
            "p99": self.percentile(99),
        }
        if samples is not None:
            out["samples"] = samples
        ex = self.exemplar
        if ex is not None:
            out["exemplar"] = ex
        return out


class Registry:
    """One process-wide instance (:data:`REGISTRY`); separate instances
    exist only for tests."""

    def __init__(self) -> None:
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._collectors: Dict[str, Callable[[], Dict[str, Dict[str, float]]]] = {}
        self._events: "collections.deque" = collections.deque(
            maxlen=EVENT_RING_CAP)
        self._events_total = 0
        self._events_path: Optional[str] = None
        self._events_fh = None
        # sink resolution happens ONCE (explicit path, else the env var);
        # a failed open stays failed — no per-event retry, no silent
        # fallback from an explicit path to the env-configured one
        self._events_resolved = False
        self._rank = _rank_from_env()

    # -- metric accessors (create-on-first-use) -------------------------
    def counter(self, name: str) -> Counter:
        with _lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with _lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str) -> Histogram:
        with _lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name)
            return h

    def histogram_items(self, prefix: str = "") -> Dict[str, Histogram]:
        with _lock:
            return {n: h for n, h in self._histograms.items()
                    if n.startswith(prefix)}

    def clear_prefix(self, prefix: str) -> None:
        """Drop metrics whose name starts with ``prefix`` (the profiling
        module's ``log_timings(reset=True)`` semantics)."""
        with _lock:
            for table in (self._counters, self._gauges, self._histograms):
                for name in [n for n in table if n.startswith(prefix)]:
                    del table[name]

    # -- collectors ------------------------------------------------------
    def register_collector(
            self, name: str,
            fn: Callable[[], Dict[str, Dict[str, float]]]) -> None:
        """``fn`` returns ``{"counters": {...}, "gauges": {...}}`` merged at
        snapshot time — for subsystems keeping their own ledgers
        (utils/sanitizer.py).  Re-registration under the same name
        replaces (idempotent module reloads)."""
        with _lock:
            self._collectors[name] = fn

    def unregister_collector(self, name: str) -> None:
        """Drop a registered collector (no-op when absent).  For
        launch-scoped collectors like the launcher's ``fleet_live``
        (which deliberately outlives its run for post-mortem scrapes of
        the LAUNCHER's endpoint): tests probing process health after a
        faulted launch must drop it, or the dead fleet's on-disk
        counters keep flipping /healthz degraded — ``reset()`` cannot,
        since the sanitizer-ledger collectors must survive it."""
        with _lock:
            self._collectors.pop(name, None)

    # -- events ----------------------------------------------------------
    def set_events_file(self, path: Optional[str]) -> None:
        """Explicit sink path; ``None`` reverts to env-var resolution
        (``LGBMTPU_EVENTS_FILE``) at the next event."""
        with _lock:
            fh, self._events_fh = self._events_fh, None
            self._events_path = path
            self._events_resolved = False
        if fh is not None:
            # close on the IO leaf lock so it serializes with in-flight
            # sink writes instead of stalling registry readers
            with _events_io_lock:
                try:
                    fh.close()  # jaxlint: disable=L2 (dedicated event-sink IO leaf lock; guards only the fh)
                except OSError:
                    pass

    def event(self, kind: str, **fields: Any) -> None:
        if not _enabled:
            return
        rec = {"ts": time.time(), "kind": kind, "rank": self._rank}
        rec.update(fields)
        with _lock:
            self._events.append(rec)
            self._events_total += 1
            if not self._events_resolved:
                self._events_resolved = True
                path = self._events_path or os.environ.get(
                    "LGBMTPU_EVENTS_FILE")
                if path:
                    try:
                        # one-time sink arm (first event only): the open
                        # stays under the registry lock so exactly one
                        # resolution wins; steady-state writes do not
                        # pass through here
                        self._events_fh = open(path, "a", encoding="utf-8")  # jaxlint: disable=L2 (one-time sink arm on the first event, not a steady-state path)
                        self._events_path = path
                    except OSError:
                        self._events_fh = None  # stays failed: no
                        # per-event retry, no fallback to another path
            fh = self._events_fh
        if fh is None:
            return
        # sink write OUTSIDE the registry lock: a slow disk stalls only
        # other event writers (this leaf lock), never counter/gauge/
        # histogram updates.  A concurrent set_events_file may have
        # detached fh since the snapshot — the identity re-check makes
        # the stale writer skip instead of writing to a closed handle.
        # File line order can differ from ring order across racing
        # events; records carry ts.
        with _events_io_lock:
            if fh is not self._events_fh:
                return
            try:
                fh.write(json.dumps(rec, default=str) + "\n")  # jaxlint: disable=L2 (dedicated event-sink IO leaf lock; guards only the fh)
                fh.flush()  # jaxlint: disable=L2 (dedicated event-sink IO leaf lock; guards only the fh)
            except (OSError, ValueError):
                with _lock:
                    if self._events_fh is fh:
                        self._events_fh = None

    def events(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        with _lock:
            out = list(self._events)
        if kind is not None:
            out = [e for e in out if e.get("kind") == kind]
        return out

    # -- snapshot --------------------------------------------------------
    def snapshot(self, include_samples: bool = False) -> Dict[str, Any]:
        with _lock:
            counters = {n: c.value for n, c in self._counters.items()}
            gauges = {n: g.value for n, g in self._gauges.items()}
            # capture the Histogram OBJECTS under the lock: a concurrent
            # clear_prefix()/reset() may drop map entries, but captured
            # objects stay summarizable
            hist_objs = dict(self._histograms)
            collectors = list(self._collectors.items())
            events_total = self._events_total
        hists = {n: h.summary(include_samples=include_samples)
                 for n, h in hist_objs.items()}
        for cname, fn in collectors:
            try:
                extra = fn() or {}
            except Exception:  # noqa: BLE001 — a broken collector must
                continue  # never take the snapshot (or a run report) down
            for n, v in (extra.get("counters") or {}).items():
                counters[n] = int(v)
            for n, v in (extra.get("gauges") or {}).items():
                gauges[n] = float(v)
        return {
            "schema": SCHEMA,
            "ts": time.time(),
            "enabled": _enabled,
            "rank": self._rank,
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
            "events_total": events_total,
        }

    def reset(self) -> None:
        """Clear metrics and events (tests only).  Registered collectors
        survive — their backing ledgers are process-cumulative and owned
        elsewhere (utils/sanitizer.py)."""
        with _lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._events.clear()
            self._events_total = 0
            self._rank = _rank_from_env()


def _percentile_of(sorted_samples: List[float], p: float) -> Optional[float]:
    if not sorted_samples:
        return None
    k = min(int(round((p / 100.0) * (len(sorted_samples) - 1))),
            len(sorted_samples) - 1)
    return sorted_samples[k]


def _rank_from_env() -> Optional[int]:
    # events/snapshots stamp the fleet-GLOBAL worker id when the launcher
    # set one: multi-slice fleets reuse slice-local rendezvous ranks per
    # slice (parallel/launcher.py), so LIGHTGBM_TPU_RANK alone would
    # attribute two different processes' records to one rank in the
    # merged fleet flight recorder
    r = os.environ.get("LGBM_TPU_WORKER_ID",
                       os.environ.get("LIGHTGBM_TPU_RANK"))
    try:
        return int(r) if r is not None else None
    except ValueError:
        return None


REGISTRY = Registry()

# module-level conveniences bound to the process registry
counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
event = REGISTRY.event
events = REGISTRY.events
snapshot = REGISTRY.snapshot
reset = REGISTRY.reset
register_collector = REGISTRY.register_collector
unregister_collector = REGISTRY.unregister_collector
set_events_file = REGISTRY.set_events_file
histogram_items = REGISTRY.histogram_items
clear_prefix = REGISTRY.clear_prefix


# ---------------------------------------------------------------------------
# snapshot persistence + validation
# ---------------------------------------------------------------------------

def _atomic_write_json(path: str, obj: Any) -> None:
    """Same-dir temp + ``os.replace``.  Deliberately NOT routed through
    utils/checkpoint.py: metrics/trace writes must not count as model
    checkpoint writes nor arm the snapshot_write fault site."""
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".tmp.", dir=d)
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump(obj, fh, indent=1, default=str)
            fh.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_snapshot(path: str, snap: Optional[Dict[str, Any]] = None,
                   include_samples: bool = False) -> None:
    """Write a snapshot as JSON, atomically.  ``include_samples`` (used by
    the per-rank periodic writer) attaches raw reservoirs so a fleet merge
    can recompute exact combined percentiles."""
    if snap is None:
        snap = snapshot(include_samples=include_samples)
    _atomic_write_json(path, snap)


def load_snapshot(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as fh:
        snap = json.load(fh)
    validate_snapshot(snap)
    return snap


def validate_snapshot(snap: Dict[str, Any]) -> None:
    """Raise ValueError unless ``snap`` is a schema-valid metrics snapshot
    (the contract bench artifacts and tests assert)."""
    if not isinstance(snap, dict) or snap.get("schema") != SCHEMA:
        raise ValueError(
            f"not a {SCHEMA} snapshot: schema={snap.get('schema')!r}"
            if isinstance(snap, dict) else "snapshot is not a JSON object")
    for key, typ in (("counters", dict), ("gauges", dict),
                     ("histograms", dict), ("events_total", int),
                     ("ts", (int, float))):
        if not isinstance(snap.get(key), typ):
            raise ValueError(f"snapshot field {key!r} missing or mistyped")
    for table in ("counters", "gauges"):
        for name, v in snap[table].items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise ValueError(
                    f"{table} entry {name!r} is not numeric: {v!r}")
    for name, h in snap["histograms"].items():
        if not isinstance(h, dict) or "count" not in h or "sum" not in h:
            raise ValueError(f"histogram {name!r} missing count/sum")


# ---------------------------------------------------------------------------
# rendering: Prometheus text exposition + reference-style log lines
# ---------------------------------------------------------------------------

def labeled(name: str, **labels: Any) -> str:
    """A metric name carrying Prometheus labels: ``labeled("x", bucket=128)``
    -> ``x{bucket="128"}``.  The registry treats the result as an opaque
    name; :func:`render_prometheus` splits it back so the exposition gets a
    real label set (and merges quantile labels for histograms).  Labels on
    an already-labeled name merge (sorted by key)."""
    base, existing = _split_labels(name)
    merged = dict(_parse_labels(existing))
    merged.update({k: str(v) for k, v in labels.items()})
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(merged.items()))
    return f"{base}{{{inner}}}" if inner else base


def _split_labels(name: str) -> tuple:
    """``x{bucket="128"}`` -> ("x", 'bucket="128"'); plain names pass
    through with an empty label string."""
    if name.endswith("}") and "{" in name:
        base, _, rest = name.partition("{")
        return base, rest[:-1]
    return name, ""


def _parse_labels(label_str: str) -> List[tuple]:
    return [(m.group(1), m.group(2)) for m in
            re.finditer(r'(\w+)="([^"]*)"', label_str)]


def _prom_name(name: str) -> str:
    return _PROM_PREFIX + re.sub(r"[^a-zA-Z0-9_]", "_", name)


def render_prometheus(snap: Optional[Dict[str, Any]] = None) -> str:
    """Prometheus text exposition (counters/gauges plus summary-style
    quantiles for histograms).  Names written via :func:`labeled` render
    with real label sets; a ``# TYPE`` line is emitted once per base
    family."""
    if snap is None:
        snap = snapshot()
    lines = [f"# lightgbm_tpu metrics ({snap.get('schema')})"]
    typed = set()

    def emit(name, typ):
        base, labels = _split_labels(name)
        pn = _prom_name(base)
        if pn not in typed:
            typed.add(pn)
            lines.append(f"# TYPE {pn} {typ}")
        return pn, labels

    for name in sorted(snap.get("counters", {})):
        pn, labels = emit(name, "counter")
        sfx = f"{{{labels}}}" if labels else ""
        lines.append(f"{pn}{sfx} {snap['counters'][name]}")
    for name in sorted(snap.get("gauges", {})):
        pn, labels = emit(name, "gauge")
        sfx = f"{{{labels}}}" if labels else ""
        lines.append(f"{pn}{sfx} {snap['gauges'][name]}")
    for name in sorted(snap.get("histograms", {})):
        h = snap["histograms"][name]
        pn, labels = emit(name, "summary")
        sfx = f"{{{labels}}}" if labels else ""
        pre = labels + "," if labels else ""
        for q, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
            v = h.get(key)
            if v is not None:
                lines.append(f'{pn}{{{pre}quantile="{q}"}} {v}')
        lines.append(f"{pn}_sum{sfx} {h.get('sum', 0.0)}")
        ex = h.get("exemplar")
        if isinstance(ex, dict) and ex.get("trace_id"):
            # OpenMetrics exemplar syntax on the count series: the trace
            # id of a witness outlier, so the latency family answers
            # "show me one real request from this tail" (the trace CLI's
            # --trace-id form reconstructs it from the flight recorder)
            lines.append(
                f"{pn}_count{sfx} {h.get('count', 0)} "
                f'# {{trace_id="{ex["trace_id"]}"}} '
                f"{ex.get('value')} {ex.get('ts')}")
        else:
            lines.append(f"{pn}_count{sfx} {h.get('count', 0)}")
    ev = snap.get("events_total")
    if ev is not None:
        pn = _prom_name("events_total")
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {ev}")
    return "\n".join(lines) + "\n"


SECTION_PREFIX = "section_seconds."


def render_lightgbm(snap: Optional[Dict[str, Any]] = None) -> List[str]:
    """Reference-log-style end-of-run report lines: the TIMETAG "Time for
    X: Y s" section tallies first, then one line per counter/gauge."""
    if snap is None:
        snap = snapshot()
    lines: List[str] = []
    hists = snap.get("histograms", {})
    sections = {n[len(SECTION_PREFIX):]: h for n, h in hists.items()
                if n.startswith(SECTION_PREFIX)}
    for name in sorted(sections, key=lambda n: -sections[n].get("sum", 0.0)):
        h = sections[name]
        lines.append(
            f"Time for {name}: {h.get('sum', 0.0):.6f} s "
            f"({h.get('count', 0)} calls)")
    for name in sorted(snap.get("counters", {})):
        lines.append(f"{name} = {snap['counters'][name]}")
    for name in sorted(snap.get("gauges", {})):
        lines.append(f"{name} = {snap['gauges'][name]:g}")
    for name in sorted(hists):
        if name.startswith(SECTION_PREFIX):
            continue
        h = hists[name]
        if not h.get("count"):
            continue
        lines.append(
            f"{name}: count={h['count']} p50={h.get('p50')} "
            f"p99={h.get('p99')} max={h.get('max')}")
    return lines


# ---------------------------------------------------------------------------
# fleet event aggregation (parallel/launcher.py)
# ---------------------------------------------------------------------------

def merge_event_files(paths: List[str], out_path: str) -> int:
    """Merge per-rank JSONL event files into one fleet-level JSONL sorted by
    timestamp; malformed lines are skipped (a crashed worker may have torn
    its last record).  Returns the number of merged records."""
    records: List[Dict[str, Any]] = []
    for p in paths:
        try:
            with open(p, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict):
                        records.append(rec)
        except OSError:
            continue
    records.sort(key=lambda r: r.get("ts", 0.0))
    with open(out_path, "w", encoding="utf-8") as fh:
        for rec in records:
            fh.write(json.dumps(rec, default=str) + "\n")
    return len(records)


# ---------------------------------------------------------------------------
# fleet metrics aggregation (parallel/launcher.py)
# ---------------------------------------------------------------------------

FLEET_SCHEMA = "lgbmtpu-fleet-metrics-v1"


def _merge_hist_summaries(summaries: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-rank histogram summaries: count/sum/min/max combine
    exactly; percentiles recompute from the concatenated reservoirs when
    the snapshots carry samples (``include_samples=True``, the per-rank
    writer default), else fall back to a count-weighted average of the
    per-rank estimates (approximate, better than dropping them)."""
    count = sum(int(s.get("count") or 0) for s in summaries)
    total = sum(float(s.get("sum") or 0.0) for s in summaries)
    mins = [s["min"] for s in summaries if s.get("min") is not None]
    maxs = [s["max"] for s in summaries if s.get("max") is not None]
    out: Dict[str, Any] = {
        "count": count, "sum": total,
        "min": min(mins) if mins else None,
        "max": max(maxs) if maxs else None,
    }
    exemplars = [s["exemplar"] for s in summaries
                 if isinstance(s.get("exemplar"), dict)
                 and s["exemplar"].get("trace_id")]
    if exemplars:
        # fleet-wide witness: the worst outlier any rank saw
        out["exemplar"] = max(
            exemplars, key=lambda e: float(e.get("value") or 0.0))
    samples: List[float] = []
    for s in summaries:
        samples.extend(s.get("samples") or [])
    if samples:
        samples.sort()
        for key, p in (("p50", 50), ("p90", 90), ("p99", 99)):
            out[key] = _percentile_of(samples, p)
        return out
    for key in ("p50", "p90", "p99"):
        num = den = 0.0
        for s in summaries:
            v, c = s.get(key), int(s.get("count") or 0)
            if v is not None and c > 0:
                num += v * c
                den += c
        out[key] = (num / den) if den else None
    return out


def merge_snapshot_files(paths: List[str],
                         out_path: Optional[str] = None) -> Dict[str, Any]:
    """Merge per-rank snapshot files into one fleet-level document (schema
    ``lgbmtpu-fleet-metrics-v1``): counters SUM, gauges MAX, histogram
    reservoirs merge (:func:`_merge_hist_summaries`), ``events_total``
    sums.  Missing or invalid rank files are skipped, not fatal — a
    crashed worker leaves whatever its periodic writer got out, possibly
    nothing, and the fleet artifact must still be written on kill paths.
    ``out_path`` additionally writes the document atomically."""
    ranks: Dict[str, Dict[str, Any]] = {}
    skipped: List[str] = []
    for i, p in enumerate(paths):
        try:
            snap = load_snapshot(p)
        except (OSError, ValueError):
            skipped.append(os.path.basename(os.fspath(p)))
            continue
        rank = snap.get("rank")
        ranks[str(rank if rank is not None else i)] = snap
    counters: Dict[str, int] = {}
    gauges: Dict[str, float] = {}
    hist_parts: Dict[str, List[Dict[str, Any]]] = {}
    events_total = 0
    for snap in ranks.values():
        for n, v in snap["counters"].items():
            counters[n] = counters.get(n, 0) + int(v)
        for n, v in snap["gauges"].items():
            gauges[n] = max(gauges.get(n, float("-inf")), float(v))
        for n, h in snap["histograms"].items():
            hist_parts.setdefault(n, []).append(h)
        events_total += int(snap.get("events_total") or 0)
    fleet = {
        "schema": FLEET_SCHEMA,
        "ts": time.time(),
        "num_ranks": len(ranks),
        "skipped": skipped,
        "ranks": ranks,
        "aggregate": {
            "counters": counters,
            "gauges": gauges,
            "histograms": {n: _merge_hist_summaries(parts)
                           for n, parts in hist_parts.items()},
            "events_total": events_total,
        },
    }
    if out_path is not None:
        _atomic_write_json(out_path, fleet)
    return fleet


def load_fleet_metrics(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as fh:
        fleet = json.load(fh)
    validate_fleet_metrics(fleet)
    return fleet


def validate_fleet_metrics(fleet: Any) -> None:
    """Raise ValueError unless ``fleet`` is a schema-valid fleet metrics
    document (one entry per rank plus the aggregate)."""
    if not isinstance(fleet, dict) or fleet.get("schema") != FLEET_SCHEMA:
        raise ValueError(
            f"not a {FLEET_SCHEMA} document: schema={fleet.get('schema')!r}"
            if isinstance(fleet, dict) else "fleet metrics not a JSON object")
    if not isinstance(fleet.get("ranks"), dict):
        raise ValueError("fleet field 'ranks' missing or mistyped")
    for rank, snap in fleet["ranks"].items():
        try:
            validate_snapshot(snap)
        except ValueError as e:
            raise ValueError(f"rank {rank}: {e}") from None
    agg = fleet.get("aggregate")
    if not isinstance(agg, dict):
        raise ValueError("fleet field 'aggregate' missing or mistyped")
    for key in ("counters", "gauges", "histograms"):
        if not isinstance(agg.get(key), dict):
            raise ValueError(f"aggregate field {key!r} missing or mistyped")


def render_prometheus_fleet(fleet: Dict[str, Any]) -> str:
    """Prometheus exposition for a fleet document: the aggregate unlabeled
    plus every per-rank series re-labeled ``{rank="<r>"}``."""
    agg = fleet["aggregate"]
    counters = dict(agg.get("counters", {}))
    gauges = dict(agg.get("gauges", {}))
    hists = dict(agg.get("histograms", {}))
    for rank, snap in sorted(fleet.get("ranks", {}).items()):
        for n, v in snap.get("counters", {}).items():
            counters[labeled(n, rank=rank)] = v
        for n, v in snap.get("gauges", {}).items():
            gauges[labeled(n, rank=rank)] = v
        for n, h in snap.get("histograms", {}).items():
            hists[labeled(n, rank=rank)] = h
    pseudo = {
        "schema": fleet.get("schema"),
        "counters": counters,
        "gauges": gauges,
        "histograms": hists,
        "events_total": agg.get("events_total"),
    }
    return render_prometheus(pseudo)


# ---------------------------------------------------------------------------
# periodic snapshot writer (per-rank flight recorder for the fleet merge)
# ---------------------------------------------------------------------------

_snap_writer_lock = threading.Lock()
_snap_writer: Optional[tuple] = None  # (thread, stop_event, path)


def start_periodic_snapshots(path: str, period_s: float = 1.0,
                             include_samples: bool = True) -> None:
    """Write the registry snapshot to ``path`` atomically NOW and then
    every ``period_s`` seconds from a daemon thread — the per-rank flight
    recorder the launcher merges into ``fleet_metrics.json``.  Writing
    first (not after the first sleep) means even a worker that dies in
    its first iteration leaves a mergeable file.  One writer per process;
    restarting moves it to the new path."""
    stop_periodic_snapshots()
    stop = threading.Event()

    def _loop() -> None:
        while True:
            try:
                write_snapshot(path, include_samples=include_samples)
            except OSError:
                pass  # a full disk must not kill the worker
            if stop.wait(max(period_s, 0.05)):
                return

    t = threading.Thread(target=_loop, daemon=True,
                         name="lgbmtpu-metrics-snapshots")
    global _snap_writer
    with _snap_writer_lock:
        _snap_writer = (t, stop, path)
    t.start()


def stop_periodic_snapshots(final_write: bool = True) -> None:
    """Stop the periodic writer; by default flush one last exact snapshot
    so a clean exit's file is not one period stale."""
    global _snap_writer
    with _snap_writer_lock:
        writer, _snap_writer = _snap_writer, None
    if writer is None:
        return
    t, stop, path = writer
    stop.set()
    t.join(timeout=5)
    if final_write:
        try:
            write_snapshot(path, include_samples=True)
        except OSError:
            pass
