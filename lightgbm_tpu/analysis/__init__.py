"""jaxlint — static purity/recompile analysis for the TPU hot paths.

CI analogue of the reference's ASan/UBSan sanitizer builds (SURVEY §6.2),
specialized to the failure modes of a jitted JAX codebase:

====  =======================  =============================================
R1    host-sync-in-hot-path    np.asarray/.item()/float() on device values
                               in traced code or jit-dispatching host loops
R2    recompile-hazard         per-call jax.jit construction; unhashable
                               static-arg literals
R3    use-after-donate         reads of a variable after it was passed in a
                               donate_argnums position
R4    collective-axis-name     psum/all_gather/... axis strings must match
                               the mesh module's declared axis constants
R5    impure-under-jit         Python RNG / time.* / global mutation inside
                               traced functions
...   (R6-R14: see docs/ANALYSIS.md for the full catalogue)
====  =======================  =============================================

A second, trace-level layer lives in :mod:`.jaxpr_audit` +
:mod:`.contracts` (rules J1-J6): it traces the registered flagship
executables hermetically and verifies the one-dispatch /
one-collective / all-donated contracts on the jaxpr — the properties
the AST rules structurally cannot see through the shared round driver's
closure dispatch.  Import it explicitly (it is not imported here, so
``lightgbm_tpu.analysis`` stays JAX-free for pre-commit use).

A third, concurrency layer lives in :mod:`.locks` (rules L1-L5): it
builds a whole-package lock model (which Lock/RLock/Condition attributes
exist, which ``with`` blocks acquire them, which attributes mutate under
which guards) and pins lock discipline — order inversions, blocking
calls under locks, unguarded shared mutations, predicate-free waits and
orphan threads.  It shares the AST layer's registry, pragma format and
stale-pragma detection; ``--locks`` selects it alone.  Its runtime twin
is :mod:`lightgbm_tpu.utils.locktrace` (witness-graph lock wrappers).

Usage::

    python -m lightgbm_tpu.analysis lightgbm_tpu/            # full package
    python -m lightgbm_tpu.analysis --rules R1,R3 ops/        # subset
    python -m lightgbm_tpu.analysis --strict-pragmas          # stale=fail
    python -m lightgbm_tpu.analysis --jaxpr                   # traced-IR audit
    python -m lightgbm_tpu.analysis --jaxpr --contract windowed_round_float

or from tests::

    from lightgbm_tpu.analysis import run
    report = run([pkg_dir])
    assert report.ok, "\\n".join(f.format() for f in report.findings)

Suppressions are inline pragmas with a mandatory reason::

    info = np.asarray(info_d)  # jaxlint: disable=R1 (the one sync per round)

See docs/ANALYSIS.md for the rule catalogue and how to add a rule.
"""

from .core import (Finding, PackageIndex, Pragma, Report, RULES,
                   register_rule, run)
from . import rules  # noqa: F401  — registers R1-R17 on import
from . import locks  # noqa: F401  — registers the concurrency layer L1-L5

__all__ = ["Finding", "PackageIndex", "Pragma", "Report", "RULES",
           "register_rule", "run"]
