"""jaxlint — static purity/recompile analysis for the TPU hot paths.

CI analogue of the reference's ASan/UBSan sanitizer builds (SURVEY §6.2),
specialized to the failure modes of a jitted JAX codebase:

====  =======================  =============================================
R1    host-sync-in-hot-path    np.asarray/.item()/float() on device values
                               in traced code or jit-dispatching host loops
R2    recompile-hazard         per-call jax.jit construction; unhashable
                               static-arg literals
R3    use-after-donate         reads of a variable after it was passed in a
                               donate_argnums position
R4    collective-axis-name     psum/all_gather/... axis strings must match
                               the mesh module's declared axis constants
R5    impure-under-jit         Python RNG / time.* / global mutation inside
                               traced functions
====  =======================  =============================================

Usage::

    python -m lightgbm_tpu.analysis lightgbm_tpu/            # full package
    python -m lightgbm_tpu.analysis --rules R1,R3 ops/        # subset

or from tests::

    from lightgbm_tpu.analysis import run
    report = run([pkg_dir])
    assert report.ok, "\\n".join(f.format() for f in report.findings)

Suppressions are inline pragmas with a mandatory reason::

    info = np.asarray(info_d)  # jaxlint: disable=R1 (the one sync per round)

See docs/ANALYSIS.md for the rule catalogue and how to add a rule.
"""

from .core import (Finding, PackageIndex, Pragma, Report, RULES,
                   register_rule, run)
from . import rules  # noqa: F401  — registers R1-R5 on import

__all__ = ["Finding", "PackageIndex", "Pragma", "Report", "RULES",
           "register_rule", "run"]
