"""CLI: ``python -m lightgbm_tpu.analysis [paths...]``.

Exit status 0 when no unsuppressed findings, 1 otherwise, 2 on bad usage —
so the pytest gate (tests/test_jaxlint_gate.py) and pre-commit runs
(helpers/run_jaxlint.py) share one entry point.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .core import RULES, run
from . import rules  # noqa: F401


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.analysis",
        description="jaxlint: JAX/TPU purity & recompile static analysis")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files/directories to scan (default: the "
                             "installed lightgbm_tpu package)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids (default: all)")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also list pragma-suppressed findings")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalogue and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            rule = RULES[rid]
            print(f"{rid}  {rule.name}")
            for line in rule.doc.splitlines():
                print(f"      {line.strip()}")
        return 0

    if args.paths:
        roots = [Path(p) for p in args.paths]
    else:
        roots = [Path(__file__).resolve().parent.parent]
    for r in roots:
        if not r.exists():
            print(f"error: no such path: {r}", file=sys.stderr)
            return 2

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rule_ids if r not in RULES]
        if unknown:
            print(f"error: unknown rules {unknown}; known: {sorted(RULES)}",
                  file=sys.stderr)
            return 2

    report = run(roots, rule_ids)
    for f in report.findings:
        print(f.format())
    if args.show_suppressed:
        for f, p in report.suppressed:
            print(f"[suppressed: {p.reason}] {f.format()}")
    n, s = len(report.findings), len(report.suppressed)
    print(f"jaxlint: {n} finding(s), {s} suppressed", file=sys.stderr)
    return 0 if report.ok else 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # e.g. `... | head` closed the pipe
        sys.exit(0)
