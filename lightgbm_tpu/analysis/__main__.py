"""CLI: ``python -m lightgbm_tpu.analysis [paths...]``.

Three layers, one entry point (docs/ANALYSIS.md):

* default — **jaxlint**, the AST pass over source (rules R1-R17 plus the
  concurrency rules L1-L5).  Runs without touching JAX device state.
  Stale pragmas (a ``disable=Rn`` whose line no longer triggers Rn) warn
  by default; ``--strict-pragmas`` promotes them to findings.
* ``--locks`` — the **concurrency layer** alone (rules L1-L5 over the
  whole-package lock model, analysis/locks.py): lock-order inversions,
  blocking calls under locks, unguarded shared mutations, predicate-free
  Condition.waits, orphan threads.
* ``--jaxpr`` — the **jaxpr executable audit** (rules J1-J6 over the
  registered contracts, analysis/contracts.py).  Traces the flagship
  executables hermetically on the host CPU; ``--contract NAME`` selects
  a subset (repeatable), ``--no-runtime`` skips the DispatchCounter
  ledger cross-check (which executes a tiny sharded training).

Exit status 0 when no unsuppressed findings, 1 otherwise, 2 on bad usage
— so the pytest gates (tests/test_jaxlint_gate.py, tests/
test_jaxpr_audit.py) and pre-commit runs (helpers/run_jaxlint.py) share
one contract.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

from .core import RULES, run
from . import rules  # noqa: F401
from . import locks  # noqa: F401  — registers L1-L5


def _ensure_loopback_devices() -> None:
    """Arm the loopback host-device env for the sharded contracts if jax
    has not loaded yet.  Under ``python -m lightgbm_tpu.analysis`` the
    parent package import pulls jax in before main() runs, so this is
    usually a no-op there — the audit then runs on however many devices
    exist (the collectives trace identically; only the lowering differs).
    helpers/run_jaxlint.py sets the flag before ANY import, and the
    pytest gate inherits conftest's 8-device flag."""
    if "jax" in sys.modules:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def _main_jaxpr(args) -> int:
    _ensure_loopback_devices()
    from . import jaxpr_audit
    from .contracts import CONTRACTS

    if args.list_contracts:
        for name in sorted(CONTRACTS):
            c = CONTRACTS[name]
            print(f"{name}  [{len(c.collectives)} collective(s), "
                  f"{len(c.donated_args)} donated arg(s)]")
            print(f"      {c.description}")
        for rid in sorted(jaxpr_audit.JAXPR_RULES):
            print(f"{rid}  {jaxpr_audit.JAXPR_RULES[rid]}")
        return 0

    names = list(args.contract) if args.contract else None
    if names:
        unknown = [n for n in names if n not in CONTRACTS]
        if unknown:
            print(f"error: unknown contracts {unknown}; known: "
                  f"{sorted(CONTRACTS)}", file=sys.stderr)
            return 2
    report = jaxpr_audit.run_jaxpr_audit(names, runtime=not args.no_runtime)
    for f in report.findings:
        print(f.format())
    if args.show_suppressed:
        for f, reason in report.waived:
            print(f"[waived: {reason}] {f.format()}")
    for r in report.results:
        coll = r.detail.get("collectives")
        extra = f", collectives: {len(coll)}" if coll is not None else ""
        print(f"jaxpr-audit: {r.name}: "
              f"{'ok' if r.ok else f'{len(r.findings)} finding(s)'}"
              f"{extra}", file=sys.stderr)
    for merge, summary in report.ledger.items():
        print(f"jaxpr-audit: ledger[{merge}]: {summary}", file=sys.stderr)
    n, w = len(report.findings), len(report.waived)
    print(f"jaxpr-audit: {n} finding(s), {w} waived", file=sys.stderr)
    return 0 if report.ok else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.analysis",
        description="jaxlint: JAX/TPU purity & recompile static analysis "
                    "(AST layer R1-R14; --jaxpr: traced-IR audit J1-J6)")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files/directories to scan (default: the "
                             "installed lightgbm_tpu package)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids (default: all)")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also list pragma-suppressed (or contract-"
                             "waived) findings")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalogue and exit")
    parser.add_argument("--strict-pragmas", action="store_true",
                        help="promote stale pragmas (suppressions whose "
                             "line no longer triggers the named rule) "
                             "from warnings to findings")
    parser.add_argument("--locks", action="store_true",
                        help="run only the concurrency layer (rules L1-L5 "
                             "over the package lock model)")
    parser.add_argument("--jaxpr", action="store_true",
                        help="run the jaxpr executable audit (J1-J6 over "
                             "the registered contracts) instead of the "
                             "AST layer")
    parser.add_argument("--contract", action="append", metavar="NAME",
                        help="audit only this contract (repeatable; "
                             "implies --jaxpr)")
    parser.add_argument("--list-contracts", action="store_true",
                        help="print the contract + J-rule catalogue and "
                             "exit (implies --jaxpr)")
    parser.add_argument("--no-runtime", action="store_true",
                        help="--jaxpr: skip the DispatchCounter ledger "
                             "cross-check (pure trace/lower, no "
                             "execution)")
    args = parser.parse_args(argv)

    if args.locks and (args.jaxpr or args.contract or args.list_contracts
                       or args.rules):
        print("error: --locks selects the L1-L5 layer and contradicts "
              "--jaxpr/--contract/--list-contracts/--rules",
              file=sys.stderr)
        return 2

    if args.jaxpr or args.contract or args.list_contracts:
        if args.paths:
            # the audit runs REGISTERED contracts, not source paths — a
            # path here means the caller expects a scoped scan it would
            # not get; fail loudly like other bad usage
            print("error: --jaxpr audits registered contracts and takes "
                  "no paths (use --contract NAME to select)",
                  file=sys.stderr)
            return 2
        return _main_jaxpr(args)

    if args.list_rules:
        for rid in sorted(RULES):
            rule = RULES[rid]
            print(f"{rid}  {rule.name}")
            for line in rule.doc.splitlines():
                print(f"      {line.strip()}")
        return 0

    if args.paths:
        roots = [Path(p) for p in args.paths]
    else:
        roots = [Path(__file__).resolve().parent.parent]
    for r in roots:
        if not r.exists():
            print(f"error: no such path: {r}", file=sys.stderr)
            return 2

    rule_ids = None
    if args.locks:
        rule_ids = [rid for rid, rule in RULES.items()
                    if rule.layer == "locks"]
    elif args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rule_ids if r not in RULES]
        if unknown:
            print(f"error: unknown rules {unknown}; known: {sorted(RULES)}",
                  file=sys.stderr)
            return 2

    report = run(roots, rule_ids, strict_pragmas=args.strict_pragmas)
    for f in report.findings:
        print(f.format())
    if args.show_suppressed:
        for f, p in report.suppressed:
            print(f"[suppressed: {p.reason}] {f.format()}")
    if report.stale and not args.strict_pragmas:
        # default-on warning: retired pragmas must not accumulate
        for f in report.stale:
            print(f"warning: {f.format()}", file=sys.stderr)
    n, s = len(report.findings), len(report.suppressed)
    print(f"jaxlint: {n} finding(s), {s} suppressed, "
          f"{len(report.stale)} stale pragma(s)", file=sys.stderr)
    return 0 if report.ok else 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # e.g. `... | head` closed the pipe
        sys.exit(0)
