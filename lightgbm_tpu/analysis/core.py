"""jaxlint core: package indexing, pragma handling, rule registry.

The reference project keeps its C++ tree learner honest with ASan/UBSan CI
builds (SURVEY §6.2).  The jit-purity analogue for this TPU-native
reproduction is a static pass over the package source: the bug classes this
codebase actually breeds are JAX-specific — hidden host syncs in hot loops,
silent per-round recompiles, reads of donated buffers, axis-name drift
between collectives and the mesh, and impure Python under trace.  None of
those are caught by type checkers or flake8; all of them are visible in the
AST.

Architecture
------------
``PackageIndex`` parses every ``.py`` file under the given roots ONCE and
builds the shared facts rules need:

* per-module ASTs, source lines and ``# jaxlint: disable=`` pragmas,
* every function definition (module-level and nested) with its jit
  decoration info (``static_argnums/argnames``, ``donate_argnums/argnames``),
* a package-local call graph (calls resolved through ``from .x import y``
  relative imports and module-level names),
* the *hot set*: functions that are jit-decorated, reachable from a
  jit-decorated function through the call graph, or host driver loops that
  dispatch a jitted function from inside ``for``/``while``,
* declared mesh axis names (module-level ``NAME_AXIS = "literal"``).

Rules live in ``rules.py`` and register themselves with ``@register_rule``;
each receives the ``PackageIndex`` and yields ``Finding`` objects.  The
runner applies pragma suppression afterwards so suppressed findings can
still be listed (``--show-suppressed``).

Pragma format (every exception must be documented)::

    x = np.asarray(d)  # jaxlint: disable=R1 (reason why this is intended)

A pragma on a comment-only line suppresses the next code line.  A pragma
without a parenthesised reason, or naming an unknown rule, is itself a
finding (``P0``) and cannot be suppressed.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Set, Tuple

PRAGMA_RE = re.compile(
    r"#\s*jaxlint:\s*disable=(?P<rules>[A-Za-z0-9_,\s]+?)"
    r"(?:\s*\((?P<reason>.*)\))?\s*$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer hit: location + rule + message + one-line fix hint."""

    file: str
    line: int
    rule: str
    message: str
    hint: str = ""

    def format(self) -> str:
        s = f"{self.file}:{self.line}: {self.rule} {self.message}"
        if self.hint:
            s += f"  | hint: {self.hint}"
        return s


@dataclasses.dataclass(frozen=True)
class Pragma:
    line: int  # line the pragma suppresses (resolved for comment-only lines)
    pragma_line: int  # line the pragma text sits on
    rules: Tuple[str, ...]
    reason: str


class JitInfo:
    """Decoration facts for a jit-wrapped function."""

    def __init__(self) -> None:
        self.static_argnums: Tuple[int, ...] = ()
        self.static_argnames: Tuple[str, ...] = ()
        self.donate_argnums: Tuple[int, ...] = ()
        self.donate_argnames: Tuple[str, ...] = ()


class FuncInfo:
    def __init__(self, module: "ModuleInfo", node: ast.FunctionDef,
                 qualname: str, parent: Optional["FuncInfo"]) -> None:
        self.module = module
        self.node = node
        self.qualname = qualname
        self.parent = parent
        self.jit: Optional[JitInfo] = _jit_info_from_decorators(node)
        self.params: Tuple[str, ...] = tuple(
            a.arg for a in (node.args.posonlyargs + node.args.args
                            + node.args.kwonlyargs))
        # resolved package-local callees: set of (modname, funcname)
        self.callees: Set[Tuple[str, str]] = set()
        # resolved jitted callees invoked from inside a for/while loop
        self.loop_jit_calls: Set[Tuple[str, str]] = set()

    @property
    def key(self) -> Tuple[str, str]:
        return (self.module.name, self.qualname)


def _const_int_tuple(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _const_str_tuple(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant) and isinstance(e.value, str))
    return ()


def is_jax_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` / bare ``jit`` reference."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        v = node.value
        return isinstance(v, ast.Name) and v.id == "jax"
    return isinstance(node, ast.Name) and node.id == "jit"


def _fill_jit_kwargs(info: JitInfo, keywords: Iterable[ast.keyword]) -> None:
    for kw in keywords:
        if kw.arg == "static_argnums":
            info.static_argnums = _const_int_tuple(kw.value)
        elif kw.arg == "static_argnames":
            info.static_argnames = _const_str_tuple(kw.value)
        elif kw.arg == "donate_argnums":
            info.donate_argnums = _const_int_tuple(kw.value)
        elif kw.arg == "donate_argnames":
            info.donate_argnames = _const_str_tuple(kw.value)


def jit_info_from_call(node: ast.Call) -> Optional[JitInfo]:
    """``jax.jit(...)`` or ``functools.partial(jax.jit, ...)`` call."""
    f = node.func
    if is_jax_jit_expr(f):
        info = JitInfo()
        _fill_jit_kwargs(info, node.keywords)
        return info
    is_partial = (
        (isinstance(f, ast.Attribute) and f.attr == "partial")
        or (isinstance(f, ast.Name) and f.id == "partial")
    )
    if is_partial and node.args and is_jax_jit_expr(node.args[0]):
        info = JitInfo()
        _fill_jit_kwargs(info, node.keywords)
        return info
    return None


def _jit_info_from_decorators(node: ast.FunctionDef) -> Optional[JitInfo]:
    for dec in node.decorator_list:
        if is_jax_jit_expr(dec):
            return JitInfo()
        if isinstance(dec, ast.Call):
            info = jit_info_from_call(dec)
            if info is not None:
                return info
    return None


def has_cache_decorator(node: ast.FunctionDef) -> bool:
    """``functools.lru_cache`` / ``functools.cache`` (bare or called)."""
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = target.attr if isinstance(target, ast.Attribute) else (
            target.id if isinstance(target, ast.Name) else "")
        if name in ("lru_cache", "cache"):
            return True
    return False


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` expression -> "a.b.c", else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class ModuleInfo:
    def __init__(self, path: Path, name: str, source: str) -> None:
        self.path = path
        self.name = name  # dotted name relative to the scan root
        self.is_package = path.name == "__init__.py"
        self.source_lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        self.pragmas: List[Pragma] = []
        self.bad_pragmas: List[Tuple[int, str]] = []  # (line, why)
        self.functions: Dict[str, FuncInfo] = {}
        # name visible at module level -> ("module", modname) for package
        # modules, or ("func", (modname, funcname)) for imported functions
        self.imports: Dict[str, Tuple[str, object]] = {}
        self.axis_constants: Dict[str, str] = {}  # NAME_AXIS -> "literal"
        self.str_constants: Dict[str, str] = {}  # any NAME -> "literal"
        self._collect_pragmas()
        self._collect_axis_constants()

    # -- pragmas ---------------------------------------------------------
    def _comment_lines(self) -> Set[int]:
        """Lines carrying a REAL comment token.  The pragma regex alone
        also matches pragma-shaped text inside string literals (the
        docstring examples in this very package) — those never suppressed
        anything, but the stale-pragma pass would flag them as retired.
        Tokenizing once keeps pragmas a comments-only construct."""
        import io
        import tokenize
        out: Set[int] = set()
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO("\n".join(self.source_lines)).readline):
                if tok.type == tokenize.COMMENT:
                    out.add(tok.start[0])
        except (tokenize.TokenError, IndentationError):
            # unterminated constructs: fall back to every line (regex-only
            # behavior) rather than silently dropping real pragmas
            return set(range(1, len(self.source_lines) + 1))
        return out

    def _collect_pragmas(self) -> None:
        comment_lines = self._comment_lines()
        for i, text in enumerate(self.source_lines, start=1):
            if i not in comment_lines:
                continue
            m = PRAGMA_RE.search(text)
            if not m:
                continue
            rules = tuple(r.strip() for r in m.group("rules").split(",")
                          if r.strip())
            reason = (m.group("reason") or "").strip()
            target = i
            if text.lstrip().startswith("#"):
                # comment-only pragma line: applies to the next CODE line
                # (skipping further comments and blank lines)
                j = i
                while j < len(self.source_lines) and (
                        not self.source_lines[j].strip()
                        or self.source_lines[j].lstrip().startswith("#")):
                    j += 1
                target = j + 1 if j < len(self.source_lines) else i
            if not reason:
                self.bad_pragmas.append(
                    (i, "pragma has no reason; write "
                        "`# jaxlint: disable=R<n> (<why>)`"))
                continue
            self.pragmas.append(Pragma(target, i, rules, reason))

    def _collect_axis_constants(self) -> None:
        for node in self.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                name = node.targets[0].id
                self.str_constants[name] = node.value.value
                if name.endswith("_AXIS"):
                    self.axis_constants[name] = node.value.value

    def suppressed(self, finding: Finding) -> Optional[Pragma]:
        for p in self.pragmas:
            if p.line == finding.line and (
                    finding.rule in p.rules or "ALL" in p.rules):
                return p
        return None


class PackageIndex:
    """Parsed view of every module under the scan roots."""

    def __init__(self, roots: Iterable[Path]) -> None:
        self.roots = [Path(r).resolve() for r in roots]
        self.modules: Dict[str, ModuleInfo] = {}
        self.errors: List[Finding] = []
        for root in self.roots:
            for path in self._iter_py(root):
                name = self._module_name(root, path)
                try:
                    src = path.read_text()
                    self.modules[name] = ModuleInfo(path, name, src)
                except (SyntaxError, UnicodeDecodeError) as e:
                    self.errors.append(Finding(
                        str(path), getattr(e, "lineno", 1) or 1, "E0",
                        f"failed to parse: {e}",
                        "fix the syntax error; jaxlint needs a valid AST"))
        self._index_functions()
        self._resolve_imports()
        self._build_call_graph()
        self.hot: Set[Tuple[str, str]] = self._compute_hot_set()
        self.axis_names: Set[str] = set()
        for mod in self.modules.values():
            self.axis_names.update(mod.axis_constants.values())

    # -- discovery -------------------------------------------------------
    @staticmethod
    def _iter_py(root: Path) -> Iterator[Path]:
        if root.is_file():
            yield root
            return
        for p in sorted(root.rglob("*.py")):
            yield p

    @staticmethod
    def _module_name(root: Path, path: Path) -> str:
        if root.is_file():
            return path.stem
        rel = path.relative_to(root).with_suffix("")
        parts = [root.name] + list(rel.parts)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    # -- function index --------------------------------------------------
    def _index_functions(self) -> None:
        for mod in self.modules.values():
            def visit(body, prefix: str, parent: Optional[FuncInfo]) -> None:
                for node in body:
                    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        qual = f"{prefix}{node.name}"
                        fi = FuncInfo(mod, node, qual, parent)
                        mod.functions[qual] = fi
                        visit(node.body, qual + ".", fi)
                    elif isinstance(node, ast.ClassDef):
                        visit(node.body, f"{prefix}{node.name}.", parent)

            visit(mod.tree.body, "", None)

    # -- imports ---------------------------------------------------------
    def _resolve_imports(self) -> None:
        for mod in self.modules.values():
            # containing package: a package module (__init__.py) IS its own
            # package — its name already lost the __init__ segment, so
            # stripping another level would resolve relative imports one
            # package too high
            pkg_parts = mod.name.split(".")
            if not mod.is_package:
                pkg_parts = pkg_parts[:-1]
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ImportFrom) and node.level > 0:
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    target = ".".join(base + (node.module or "").split("."))
                    target = target.rstrip(".")
                    for alias in node.names:
                        local = alias.asname or alias.name
                        sub = f"{target}.{alias.name}"
                        # `from ..ops import predict` imports the SUBMODULE
                        # predict, not a function — check that first (the
                        # parent package's __init__ is always indexed, so
                        # "target in modules" alone cannot discriminate)
                        if sub in self.modules:
                            mod.imports[local] = ("module", sub)
                        elif target in self.modules:
                            mod.imports[local] = ("func", (target, alias.name))

    def _resolve_export(self, modname: str, funcname: str,
                        _seen: Optional[Set[Tuple[str, str]]] = None
                        ) -> Optional[Tuple[str, str]]:
        """Find the defining module of `modname.funcname`, following
        re-export chains (`__init__.py` doing `from .impl import f`)."""
        mod = self.modules.get(modname)
        if mod is None:
            return None
        if funcname in mod.functions:
            return (modname, funcname)
        imp = mod.imports.get(funcname)
        if imp and imp[0] == "func":
            key = (modname, funcname)
            _seen = _seen or set()
            if key in _seen:
                return None
            _seen.add(key)
            return self._resolve_export(imp[1][0], imp[1][1], _seen)
        return None

    def resolve_call(self, mod: ModuleInfo, func_expr: ast.AST
                     ) -> Optional[Tuple[str, str]]:
        """Resolve a call's target to a (modname, funcname) in the package."""
        if isinstance(func_expr, ast.Name):
            name = func_expr.id
            if name in mod.functions:
                return (mod.name, name)
            imp = mod.imports.get(name)
            if imp and imp[0] == "func":
                return self._resolve_export(imp[1][0], imp[1][1])
        elif isinstance(func_expr, ast.Attribute) and isinstance(
                func_expr.value, ast.Name):
            imp = mod.imports.get(func_expr.value.id)
            if imp and imp[0] == "module":
                return self._resolve_export(imp[1], func_expr.attr)
        return None

    def lookup(self, key: Tuple[str, str]) -> Optional[FuncInfo]:
        mod = self.modules.get(key[0])
        return mod.functions.get(key[1]) if mod else None

    # -- call graph ------------------------------------------------------
    def _build_call_graph(self) -> None:
        for mod in self.modules.values():
            for fi in mod.functions.values():
                # direct statements only (nested defs carry their own edges)
                own_nodes = self._own_body_walk(fi)
                loop_nodes = self._loop_body_walk(fi)
                for node in own_nodes:
                    if isinstance(node, ast.Call):
                        target = self.resolve_call(mod, node.func)
                        if target is not None:
                            fi.callees.add(target)
                            callee = self.lookup(target)
                            if (node in loop_nodes and callee is not None
                                    and callee.jit is not None):
                                fi.loop_jit_calls.add(target)

    @staticmethod
    def _own_body_walk(fi: FuncInfo) -> List[ast.AST]:
        """All nodes in fi's body EXCLUDING nested function bodies."""
        out: List[ast.AST] = []

        def rec(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                out.append(child)
                rec(child)

        for stmt in fi.node.body:  # body only: decorators are not "inside"
            out.append(stmt)
            rec(stmt)
        return out

    @staticmethod
    def _loop_body_walk(fi: FuncInfo) -> Set[ast.AST]:
        """Nodes inside a for/while in fi's own body (no nested defs)."""
        out: Set[ast.AST] = set()

        def rec(node: ast.AST, in_loop: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                child_in_loop = in_loop or isinstance(
                    child, (ast.For, ast.While))
                if in_loop:
                    out.add(child)
                rec(child, child_in_loop)

        for stmt in fi.node.body:
            rec(stmt, isinstance(stmt, (ast.For, ast.While)))
        return out

    # -- hot set ---------------------------------------------------------
    def _compute_hot_set(self) -> Set[Tuple[str, str]]:
        """Jit-decorated functions plus everything reachable from them."""
        hot: Set[Tuple[str, str]] = set()
        stack: List[Tuple[str, str]] = []
        for mod in self.modules.values():
            for fi in mod.functions.values():
                if fi.jit is not None:
                    hot.add(fi.key)
                    stack.append(fi.key)
        while stack:
            fi = self.lookup(stack.pop())
            if fi is None:
                continue
            for target in fi.callees:
                if target not in hot:
                    hot.add(target)
                    stack.append(target)
        return hot

    def is_hot(self, fi: FuncInfo) -> bool:
        """In the traced hot path: jitted, jit-reachable, or nested in one."""
        cur: Optional[FuncInfo] = fi
        while cur is not None:
            if cur.key in self.hot:
                return True
            cur = cur.parent
        return False

    def is_host_driver(self, fi: FuncInfo) -> bool:
        """Host loop dispatching a jitted function per iteration."""
        return bool(fi.loop_jit_calls) and not self.is_hot(fi)


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

RuleFn = Callable[[PackageIndex], Iterable[Finding]]


@dataclasses.dataclass(frozen=True)
class Rule:
    rule_id: str
    name: str
    fn: RuleFn
    doc: str
    # which analysis layer the rule belongs to: "ast" (jaxlint R-rules) or
    # "locks" (the concurrency layer L-rules).  The CLI's --locks flag and
    # helpers/run_jaxlint.py's --locks-only select by layer; a plain run
    # executes every registered rule regardless of layer.
    layer: str = "ast"


RULES: Dict[str, Rule] = {}


def register_rule(rule_id: str, name: str,
                  layer: str = "ast") -> Callable[[RuleFn], RuleFn]:
    def deco(fn: RuleFn) -> RuleFn:
        if rule_id in RULES:
            raise ValueError(f"duplicate rule id {rule_id}")
        RULES[rule_id] = Rule(rule_id, name, fn, (fn.__doc__ or "").strip(),
                              layer)
        return fn

    return deco


@dataclasses.dataclass
class Report:
    findings: List[Finding]
    suppressed: List[Tuple[Finding, Pragma]]
    # pragmas whose line no longer triggers a rule they name: each entry is
    # a ready-to-print Finding (rule "P1") pointing at the pragma line.
    # Default-on WARNING (the CLI prints them to stderr); --strict-pragmas
    # promotes them into `findings` so retired suppressions cannot
    # accumulate silently (the per-round R1 pragma retired in round 7 is
    # the precedent this guards).
    stale: List[Finding] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings


def run(roots: Iterable[Path], rule_ids: Optional[Iterable[str]] = None,
        strict_pragmas: bool = False) -> Report:
    """Run the selected rules over the roots; apply pragma suppression.

    ``strict_pragmas`` promotes stale-pragma findings (P1: a
    ``disable=Rn`` whose line no longer triggers rule Rn) from warnings
    into real findings.  Staleness is only judged for rules that were
    actually selected this run — a subset run cannot conclude anything
    about an unselected rule's pragmas."""
    from . import rules as _rules  # noqa: F401  (registers built-in rules)
    from . import locks as _locks  # noqa: F401  (registers L1-L5)

    pkg = PackageIndex(roots)
    selected = sorted(rule_ids) if rule_ids else sorted(RULES)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise ValueError(f"unknown rule ids: {unknown} (have {sorted(RULES)})")

    raw: List[Finding] = list(pkg.errors)
    for rid in selected:
        raw.extend(RULES[rid].fn(pkg))

    # pragma validation: unknown rule names and missing reasons are findings
    for mod in pkg.modules.values():
        for line, why in mod.bad_pragmas:
            raw.append(Finding(str(mod.path), line, "P0", why,
                               "document every suppression with a reason"))
        for p in mod.pragmas:
            for rid in p.rules:
                if rid != "ALL" and rid not in RULES:
                    raw.append(Finding(
                        str(mod.path), p.pragma_line, "P0",
                        f"pragma names unknown rule {rid!r}",
                        f"known rules: {', '.join(sorted(RULES))}"))

    path_to_mod = {str(m.path): m for m in pkg.modules.values()}
    findings: List[Finding] = []
    suppressed: List[Tuple[Finding, Pragma]] = []
    for f in raw:
        mod = path_to_mod.get(f.file)
        p = mod.suppressed(f) if (mod and f.rule != "P0") else None
        if p is not None:
            suppressed.append((f, p))
        else:
            findings.append(f)

    # stale-pragma detection: a suppression whose target line no longer
    # triggers the rule it names.  Judged against the RAW findings (before
    # suppression), per named rule, only for rules selected this run.
    triggered = {(f.file, f.line, f.rule) for f in raw}
    triggered_lines = {(f.file, f.line) for f in raw}
    sel = set(selected)
    stale: List[Finding] = []
    for mod in pkg.modules.values():
        for p in mod.pragmas:
            for rid in p.rules:
                if rid == "ALL":
                    if (sel == set(RULES)
                            and (str(mod.path), p.line) not in triggered_lines):
                        stale.append(Finding(
                            str(mod.path), p.pragma_line, "P1",
                            "stale pragma: disable=ALL but line "
                            f"{p.line} triggers no rule at all",
                            "delete the retired suppression"))
                elif rid in RULES and rid in sel and (
                        str(mod.path), p.line, rid) not in triggered:
                    stale.append(Finding(
                        str(mod.path), p.pragma_line, "P1",
                        f"stale pragma: disable={rid} but line {p.line} "
                        f"no longer triggers {rid}",
                        "delete the retired suppression (reason: "
                        f"{p.reason!r})"))
    stale.sort(key=lambda f: (f.file, f.line))
    if strict_pragmas:
        findings.extend(stale)

    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return Report(findings=findings, suppressed=suppressed, stale=stale)
