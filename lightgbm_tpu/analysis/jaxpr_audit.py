"""Jaxpr-level executable audit: verify the one-dispatch /
one-collective / all-donated contracts on the TRACED IR, not the Python
source (docs/ANALYSIS.md "Jaxpr audit layer").

The AST layer (rules.py R1-R14) reads source; this layer traces the
registered flagship executables (contracts.py) hermetically on the host
CPU and checks per-executable **J rules** on the jaxpr and the lowered
StableHLO:

====  ==========================  ========================================
J1    collective-count/axis-name  exactly the declared collectives, on
                                  declared mesh axes, in declared order;
                                  merge variants share the protocol spine
J2    donation-consumed           every live donated invar structurally
                                  matches an output buffer, and — where
                                  the platform lowers aliasing — is
                                  actually aliased (``tf.aliasing_output``)
J3    no-f64-promotion            no convert_element_type to f64, no f64
                                  aval anywhere in the body
J4    no-host-callback            no pure_callback / io_callback /
                                  debug_callback inside a budget-pinned
                                  executable
J5    transfer-free-body          no device_put inside the trace; no baked
                                  constant above the contract's byte
                                  threshold
J6    live-set bound              a conservative peak-live-bytes estimate
                                  over the jaxpr stays under the
                                  contract's HBM budget
====  ==========================  ========================================

This closes the closure-dispatch blind spot the AST rules document: the
shared ``_run_fused_rounds`` driver dispatches its round through a
closure parameter, so R1/R6/R13 cannot see INSIDE the round — but the
round's jaxpr can be audited directly, and the runtime DispatchCounter
budget is cross-checked against the auditor's collective count
(:func:`ledger_crosscheck`): one dispatch per round on the ledger means
every audited collective rode that single dispatch.

Findings render through the same :class:`~.core.Finding` reporter as the
lint layer; suppression is by **contract-level waiver** (contracts.py
``waivers={"J6": "reason"}``) with the same mandatory-reason hygiene
(P0 on a reasonless or unknown-rule waiver).

JAX is imported lazily — importing this module costs nothing; the CLI
(`python -m lightgbm_tpu.analysis --jaxpr`) arms the loopback-device env
before the first builder runs.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

from .contracts import CONTRACTS, Contract, Target
from .core import Finding

# J-rule catalogue for --list-rules-style output
JAXPR_RULES: Dict[str, str] = {
    "J1": "collective-count/axis-name — exact declared sequence, declared "
          "mesh axes, family-consistent protocol spine, per-axis byte "
          "accounting (dcn_max_bytes pins the cross-slice bill)",
    "J2": "donation-consumed — every live donated invar aliasable (and "
          "aliased where the platform lowers aliasing)",
    "J3": "no-f64-promotion — no f64 cast or aval in the body",
    "J4": "no-host-callback — no pure/io/debug callback under the budget "
          "pin",
    "J5": "transfer-free-body — no in-trace device_put, no oversized "
          "baked constant",
    "J6": "live-set bound — conservative peak live bytes within the "
          "contract budget",
    "J7": "hbm-sweep-bound — statically estimated bin-matrix bytes read "
          "per round body within the contract's sweep budget",
}

# jax collective primitives -> the spelling contracts declare
_COLLECTIVE_PRIMS = {
    "psum": "psum", "psum2": "psum", "pmax": "pmax", "pmin": "pmin",
    "pmean": "pmean", "reduce_scatter": "psum_scatter",
    "all_gather": "all_gather", "all_to_all": "all_to_all",
    "ppermute": "ppermute", "axis_index": "axis_index",
}
_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback")

# a collective moving at least this many operand bytes is a "large" merge
# (the histogram-class collective); everything below is scalar protocol
# traffic (info-vector merges, winner election).  The headline invariant
# — ONE large in-dispatch collective per merge strategy — is asserted on
# this split by tests/test_jaxpr_audit.py.
_LARGE_COLLECTIVE_BYTES = 4096


@dataclasses.dataclass
class ContractResult:
    name: str
    findings: List[Finding]
    waived: List[Tuple[Finding, str]]  # (finding, waiver reason)
    detail: Dict[str, object]

    @property
    def ok(self) -> bool:
        return not self.findings


@dataclasses.dataclass
class JaxprReport:
    results: List[ContractResult]
    ledger: Dict[str, dict]

    @property
    def findings(self) -> List[Finding]:
        return [f for r in self.results for f in r.findings]

    @property
    def waived(self) -> List[Tuple[Finding, str]]:
        return [w for r in self.results for w in r.waived]

    @property
    def ok(self) -> bool:
        return not self.findings


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------

def _is_var(v) -> bool:
    """True for real jaxpr Vars (Literals are unhashable constants)."""
    import jax.core as jc
    return isinstance(v, jc.Var)


def _sub_jaxprs(eqn):
    import jax.core as jc
    for v in eqn.params.values():
        if isinstance(v, jc.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jc.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for vv in v:
                if isinstance(vv, jc.ClosedJaxpr):
                    yield vv.jaxpr
                elif isinstance(vv, jc.Jaxpr):
                    yield vv


def iter_eqns(jaxpr):
    """Every equation in the (open) jaxpr, recursing through call/pjit/
    shard_map/scan/cond sub-jaxprs, in trace order."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


def _eqn_axes(eqn) -> Tuple[str, ...]:
    ax = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if not isinstance(ax, (tuple, list)):
        ax = (ax,)
    return tuple(a for a in ax if isinstance(a, str))


def collect_collectives(jaxpr) -> List[Tuple[str, Tuple[str, ...], int]]:
    """Ordered (normalized-name, axis-names, max-operand-bytes) for every
    collective in the traced program."""
    out = []
    for eqn in iter_eqns(jaxpr):
        name = _COLLECTIVE_PRIMS.get(eqn.primitive.name)
        if name is None:
            continue
        nbytes = max((_aval_bytes(v.aval) for v in eqn.invars
                      if hasattr(v, "aval")), default=0)
        out.append((name, _eqn_axes(eqn), nbytes))
    return out


# ---------------------------------------------------------------------------
# J checks
# ---------------------------------------------------------------------------

def _finding(c: Contract, rule: str, msg: str, hint: str) -> Finding:
    return Finding(c.file, c.line, rule, f"[{c.name}] {msg}", hint)


def _declared_axes() -> set:
    from ..parallel.mesh import DATA_AXIS, DCN_AXIS, FEATURE_AXIS, ICI_AXIS
    return {DATA_AXIS, FEATURE_AXIS, ICI_AXIS, DCN_AXIS}


def dcn_axis_bytes(found) -> int:
    """Total operand bytes of every collective whose axes include the
    DCN axis — the per-round cross-slice byte bill the hierarchical
    contracts pin statically (``dcn_max_bytes``).  Scalar protocol
    merges that span both axes count too (they cross DCN); intra-slice
    merges on the ici axis alone do not."""
    from ..parallel.mesh import DCN_AXIS
    return sum(nb for _name, axes, nb in found if DCN_AXIS in axes)


def axis_bytes(found) -> Dict[str, int]:
    """Per-axis collective byte bill: total operand bytes of every
    collective whose axes include each mesh axis.  A both-axes scalar
    merge bills BOTH axes (it crosses both).  This is the generic form
    of ``dcn_axis_bytes`` — every contract's bill rides ``verdict()``
    into bench artifacts, so a chip row shows at a glance where a
    round's collective traffic lands on the (dcn, feature, row) grid."""
    out: Dict[str, int] = {}
    for _name, axes, nb in found:
        for ax in axes:
            out[ax] = out.get(ax, 0) + nb
    return out


def _check_dcn_bytes(c: Contract, found
                     ) -> Tuple[List[Finding], Dict[str, object]]:
    """The per-axis half of J1 (analogous to J7's sweep bound): the
    statically summed DCN-axis operand bytes per round body must stay
    under the contract's ``dcn_max_bytes`` — ≤ top-k histograms' worth.
    A full-F histogram merge smuggled onto the dcn axis fails here (and
    jaxlint R17 flags the source form)."""
    if c.dcn_max_bytes is None:
        return [], {}
    got = dcn_axis_bytes(found)
    findings = []
    if got > c.dcn_max_bytes:
        findings.append(_finding(
            c, "J1",
            f"{got} bytes of collective operands cross the dcn axis per "
            f"round, exceeding the {c.dcn_max_bytes}-byte contract pin",
            "the hierarchical merge's whole point is that only "
            "top-k-shaped or scalar operands cross DCN — route new "
            "cross-slice traffic through the top-k election "
            "(parallel/hierarchy.py::dcn_topk_best) or raise the budget "
            "consciously (docs/ANALYSIS.md, jaxlint R17)"))
    return findings, {"dcn_bytes": got}


def _check_feature_bytes(c: Contract, found
                         ) -> Tuple[List[Finding], Dict[str, object]]:
    """The 2-D layout's axis-bill pin (the feature-axis twin of
    ``_check_dcn_bytes``): collective operand bytes crossing the feature
    axis per round must stay under ``feature_max_bytes`` — the winner's
    go/no-go row broadcast plus election scalars.  A histogram merge
    smuggled onto the feature axis fails here (jaxlint R20 flags the
    source form; the exact J1 sequence pin is the ordering half)."""
    if c.feature_max_bytes is None:
        return [], {}
    from ..parallel.mesh import FEATURE_AXIS
    got = sum(nb for _name, axes, nb in found if FEATURE_AXIS in axes)
    findings = []
    if got > c.feature_max_bytes:
        findings.append(_finding(
            c, "J1",
            f"{got} bytes of collective operands cross the feature axis "
            f"per round, exceeding the {c.feature_max_bytes}-byte "
            "contract pin",
            "the 2-D layout makes the owned feature block's histograms "
            "complete locally — only the winner's row decisions and "
            "election scalars may cross the feature axis "
            "(parallel/feature2d.py, jaxlint R20); route new traffic "
            "through the election or raise the budget consciously"))
    return findings, {"feature_bytes": got}


def _check_j1(c: Contract, found) -> Tuple[List[Finding], List[str]]:
    """``found`` is the ``collect_collectives`` result — walked once by
    the caller and shared with the large-collective detail."""
    tokens = []
    findings = []
    declared_axes = _declared_axes()
    for name, axes, _nb in found:
        for ax in axes:
            if ax not in declared_axes:
                findings.append(_finding(
                    c, "J1",
                    f"collective {name} uses undeclared axis {ax!r}",
                    "collectives must ride the mesh axes parallel/mesh.py "
                    "declares (DATA_AXIS / FEATURE_AXIS)"))
        tokens.append(f"{name}@{','.join(axes) if axes else '?'}")
    if tuple(tokens) != c.collectives:
        findings.append(_finding(
            c, "J1",
            f"collective sequence mismatch: traced {len(tokens)} "
            f"({' '.join(tokens) or 'none'}), declared "
            f"{len(c.collectives)} ({' '.join(c.collectives) or 'none'})",
            "a collective entered or left the traced round body — if "
            "intentional, update the contract declaration next to the "
            "code (analysis/contracts.py); a SECOND large merge or a "
            "host-loop collective is the regression class R13 cannot see "
            "through the closure dispatch"))
    return findings, tokens


def _check_family_spine(results: Dict[str, "ContractResult"]) -> List[Finding]:
    """Merge variants of one family must share the declared protocol
    spine (prefix/suffix of the collective sequence) — the 'same order
    across merge variants' half of J1."""
    by_family: Dict[str, List[Contract]] = {}
    for name, c in CONTRACTS.items():
        if c.family and c.spine != (0, 0) and name in results:
            by_family.setdefault(c.family, []).append(c)
    findings = []
    for family, members in by_family.items():
        if len(members) < 2:
            continue
        pre = min(c.spine[0] for c in members)
        suf = min(c.spine[1] for c in members)
        ref = members[0]
        for c in members[1:]:
            if (c.collectives[:pre] != ref.collectives[:pre]
                    or (suf and c.collectives[-suf:]
                        != ref.collectives[-suf:])):
                findings.append(_finding(
                    c, "J1",
                    f"family {family!r}: protocol spine diverges from "
                    f"{ref.name} (shared prefix {pre} / suffix {suf})",
                    "merge variants must keep the round protocol's "
                    "collective order identical — only the declared "
                    "merge/election block may differ"))
    return findings


def _flat_arg_leaves(target: Target):
    """Flatten the positional args the way jax.jit does, returning
    (leaf avals, per-arg leaf index ranges)."""
    import jax.tree_util as jtu
    leaves = []
    ranges = []
    for a in target.args:
        ls = jtu.tree_leaves(a)
        ranges.append((len(leaves), len(leaves) + len(ls)))
        leaves.extend(ls)
    return leaves, ranges


def _check_j2(c: Contract, target: Target, jaxpr, lowered_text: str
              ) -> Tuple[List[Finding], Dict[str, object]]:
    import jax.tree_util as jtu
    findings: List[Finding] = []
    if not c.donated_args:
        return findings, {"donated_leaves": 0}
    _leaves, ranges = _flat_arg_leaves(target)
    donated_idx = set()
    for ai in c.donated_args:
        lo, hi = ranges[ai]
        donated_idx.update(range(lo, hi))
    jx = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    invars = jx.invars
    used = set()
    for eqn in jx.eqns:
        used.update(v for v in eqn.invars if _is_var(v))
    used.update(v for v in jx.outvars if _is_var(v))
    live_donated = [i for i in donated_idx
                    if i < len(invars) and invars[i] in used]

    # donated leaf -> (owning arg position, human path) for the message
    paths = []
    for ai, a in enumerate(target.args):
        paths.extend((ai, jtu.keystr(p)) for p, _ in
                     jtu.tree_flatten_with_path(a)[0])

    # structural consumability: every live donated invar must claim an
    # output buffer of identical aval.  Duplicate outvars count ONCE (a
    # dup output is forwarded, not a second buffer) — the class XLA
    # "drops with a warning" and the runtime CPU tier can never observe.
    avail: Dict[Tuple, int] = {}
    seen_out = set()
    for v in jx.outvars:
        if not _is_var(v) or id(v) in seen_out:
            continue
        seen_out.add(id(v))
        key = (getattr(v.aval, "shape", None),
               str(getattr(v.aval, "dtype", None)))
        avail[key] = avail.get(key, 0) + 1
    unmatched = []
    for i in live_donated:
        key = (getattr(invars[i].aval, "shape", None),
               str(getattr(invars[i].aval, "dtype", None)))
        if avail.get(key, 0) > 0:
            avail[key] -= 1
        else:
            unmatched.append(i)
    for i in unmatched:
        arg_pos, leaf_path = paths[i]
        findings.append(_finding(
            c, "J2",
            f"donated buffer arg{arg_pos}{leaf_path} "
            f"{invars[i].aval.str_short()} matches no free output buffer "
            "— XLA will warn once and silently copy every call",
            "thread the donated state linearly (same pytree structure/"
            "avals out as in) so every donated buffer can be reused in "
            "place; see docs/ANALYSIS.md J2"))

    # lowered-aliasing confirmation: where the platform lowering carries
    # tf.aliasing_output (single-device CPU/TPU), every live donated
    # buffer that SURVIVES lowering must carry the attr.  Two sanctioned
    # gaps, both measured on the flagship round: (a) the multi-device CPU
    # lowering drops aliasing wholesale (attrs == 0) — the structural
    # check above is the platform-independent half there; (b) lowering
    # DCE drops dead args entirely (keep_unused=False), and a donor the
    # executable never reads costs nothing — so the bound allows exactly
    # as much slack as the number of args lowering dropped.
    aliased = len(re.findall(r"tf\.aliasing_output", lowered_text))
    total_leaves = len(_leaves)
    m = re.search(r"func\.func public @main\((.*?)\)\s*->", lowered_text,
                  re.S)
    lowered_args = (len(re.findall(r"%arg\d+:", m.group(1)))
                    if m else total_leaves)
    dce_slack = max(total_leaves - lowered_args, 0)
    detail = {"donated_leaves": len(donated_idx),
              "live_donated_leaves": len(live_donated),
              "aliased_in_lowering": aliased,
              "lowering_dce_slack": dce_slack}
    if aliased and not unmatched and aliased < len(live_donated) - dce_slack:
        missing = len(live_donated) - dce_slack - aliased
        findings.append(_finding(
            c, "J2",
            f"{missing} live donated buffer(s) lost their aliasing in "
            f"lowering ({aliased}/{len(live_donated)} aliased, "
            f"{dce_slack} dropped by lowering DCE)",
            "a donation the jaxpr could consume was dropped at lowering "
            "— check for output forwarding or sharding mismatches"))
    return findings, detail


def _check_j3(c: Contract, jaxpr) -> List[Finding]:
    """Report f64 only where it ENTERS the trace (an f64 input, or an
    equation producing f64 from non-f64 operands — which includes every
    cast).  One leak flows through most of the downstream body, so
    flagging every f64-touching equation would flood the report and bury
    other findings; the entry points are also where the fix lives."""
    import numpy as np
    findings = []
    f64 = np.dtype("float64")

    def _is_f64(v) -> bool:
        return getattr(getattr(v, "aval", None), "dtype", None) == f64

    jx = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    for v in list(jx.constvars) + list(jx.invars):
        if _is_f64(v):
            findings.append(_finding(
                c, "J3",
                f"f64 input/const to the traced body ({v.aval.str_short()})",
                "cast at the host API boundary; the TPU round/predict "
                "bodies are f32/int programs"))
    for eqn in iter_eqns(jx):
        if any(_is_f64(v) for v in eqn.outvars) and not any(
                _is_f64(v) for v in eqn.invars):
            what = ("convert_element_type to float64"
                    if eqn.primitive.name == "convert_element_type"
                    else f"{eqn.primitive.name} producing f64 from "
                         "non-f64 operands")
            findings.append(_finding(
                c, "J3", f"{what} inside the traced body",
                "a f64 promotion entered the trace (x64 constant or "
                "cast) — keep f64 on the host API boundary; doubles "
                "bytes and falls off the MXU"))
    return findings


def _check_j4(c: Contract, jaxpr) -> List[Finding]:
    findings = []
    for eqn in iter_eqns(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr):
        if eqn.primitive.name in _CALLBACK_PRIMS:
            findings.append(_finding(
                c, "J4",
                f"{eqn.primitive.name} inside a budget-pinned executable",
                "host callbacks serialize the device queue at every call "
                "— the 1-dispatch/0-sync budget cannot hold; move the "
                "host work to the async info protocol"))
    return findings


def _check_j5(c: Contract, jaxpr) -> Tuple[List[Finding], Dict[str, object]]:
    findings = []
    for eqn in iter_eqns(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr):
        if eqn.primitive.name == "device_put":
            findings.append(_finding(
                c, "J5",
                "device_put inside the traced body",
                "transfers belong outside the executable; pass the value "
                "as an argument"))
    const_bytes = 0
    biggest = 0
    for const in getattr(jaxpr, "consts", ()):
        nb = getattr(const, "nbytes", 0) or 0
        const_bytes += nb
        biggest = max(biggest, nb)
        if nb > c.max_const_bytes:
            shape = getattr(const, "shape", "?")
            findings.append(_finding(
                c, "J5",
                f"baked constant of {nb} bytes (shape {shape}) exceeds "
                f"the {c.max_const_bytes}-byte contract threshold",
                "a closure captured a concrete array into the trace — "
                "every dispatch re-uploads it; thread it as an argument"))
    return findings, {"const_bytes": const_bytes, "largest_const": biggest}


def peak_live_bytes(jaxpr) -> int:
    """Conservative peak-live-bytes estimate over the jaxpr: classic
    linear-scan liveness (a var is live from its defining equation to its
    last use; invars from entry; outvars to exit) plus, at each call-like
    equation, the recursive peak of its sub-jaxprs (an overestimate —
    outer operands are counted again inside — which is the safe
    direction for a budget gate)."""
    jx = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    n = len(jx.eqns)
    last_use: Dict[object, int] = {}
    def_idx: Dict[object, int] = {}
    for v in jx.invars:
        def_idx[v] = 0
    for i, eqn in enumerate(jx.eqns):
        for v in eqn.invars:
            if _is_var(v):
                last_use[v] = i
        for v in eqn.outvars:
            if _is_var(v):
                def_idx[v] = i
    for v in jx.outvars:
        if _is_var(v):
            last_use[v] = n
    base = sum(_aval_bytes(cv.aval) for cv in jx.constvars)
    # event sweep
    add_at: Dict[int, int] = {}
    del_after: Dict[int, int] = {}
    for v, d in def_idx.items():
        b = _aval_bytes(getattr(v, "aval", None))
        if not b or v not in last_use:
            continue
        add_at[d] = add_at.get(d, 0) + b
        del_after[last_use[v]] = del_after.get(last_use[v], 0) + b
    live = base + add_at.get(0, 0)
    # vars defined at 0 == invars; eqn 0's outvars also say def 0 — fold
    # them in before the sweep step for i=0 (conservative)
    peak = live
    for i, eqn in enumerate(jx.eqns):
        if i > 0:
            live += add_at.get(i, 0)
        inner = max((peak_live_bytes(s) for s in _sub_jaxprs(eqn)),
                    default=0)
        peak = max(peak, live + inner)
        live -= del_after.get(i, 0)
    return peak


# ---------------------------------------------------------------------------
# J7: bin-matrix sweep estimate
# ---------------------------------------------------------------------------

# layout-movement primitives: reading a tracked array through these is a
# bin-matrix read, and their matrix-scale outputs stay tracked (the
# materialized window copy the three-pass round re-reads).  Compute
# primitives (arithmetic, convert_element_type, the scatter itself) charge
# their tracked-operand read but do NOT propagate: the first compute
# consumer is the chain's final charged read — the rule that makes the
# estimate the ROADMAP's "three passes over the bins" (gather + transpose
# + the histogram's int cast), not a count of every downstream artifact.
_J7_GATHER_PRIMS = {"gather", "dynamic_slice", "slice"}
_J7_MOVE_PRIMS = {"transpose", "reshape", "copy", "squeeze", "rev",
                  "broadcast_in_dim"}
_J7_CALL_PRIMS = {"pjit", "closed_call", "core_call", "shard_map"}


def _j7_sub_jaxpr(eqn):
    import jax.core as jc
    sub = eqn.params.get("jaxpr")
    if isinstance(sub, jc.ClosedJaxpr):
        return sub.jaxpr
    return sub


def bin_sweep_bytes(jaxpr, seed_vars, matrix_elems: int,
                    matrix_bytes: int) -> int:
    """Walk the jaxpr charging every read of the bin matrix or a
    matrix-scale array derived from it by pure layout movement.

    Charges: gather-family reads cost ``out_elems x src_itemsize`` (you
    read what you fetch — a W-column window gather reads W*F elements
    however large N is); movement/compute reads cost the tracked
    operand's bytes; a ``pallas_call`` consuming the matrix is charged
    exactly ONE sweep — the kernel contract (HBM-resident ``ANY`` refs,
    per-chunk DMA, every window column fetched once) is what jaxlint R11
    and the kernel's own parity tests verify, and the single charge is
    what makes the FUSION count visible next to the three separate
    charges the three-pass body accrues.  Control-flow bodies
    (scan/while/cond) are charged one conservative operand read without
    recursion — no audited round threads the matrix through them."""
    jx = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr

    def elems(v) -> int:
        n = 1
        for d in getattr(getattr(v, "aval", None), "shape", ()):
            n *= int(d)
        return n

    def walk(jxp, tracked) -> int:
        charged = 0
        for eqn in jxp.eqns:
            hit = [v for v in eqn.invars if _is_var(v) and v in tracked]
            if not hit:
                continue
            name = eqn.primitive.name
            if name in _J7_CALL_PRIMS:
                sub = _j7_sub_jaxpr(eqn)
                if sub is None:
                    charged += sum(_aval_bytes(v.aval) for v in hit)
                    continue
                inner = {iv for ov, iv in zip(eqn.invars, sub.invars)
                         if _is_var(ov) and ov in tracked}
                charged += walk(sub, inner)
                for sv, ov in zip(sub.outvars, eqn.outvars):
                    if _is_var(sv) and sv in inner:
                        tracked.add(ov)
                continue
            if name == "pallas_call":
                charged += matrix_bytes  # one sweep by kernel contract
                continue
            if name in _J7_GATHER_PRIMS:
                out_e = sum(elems(v) for v in eqn.outvars)
                charged += out_e * hit[0].aval.dtype.itemsize
            else:
                charged += sum(_aval_bytes(v.aval) for v in hit)
            if name in (_J7_GATHER_PRIMS | _J7_MOVE_PRIMS):
                for v in eqn.outvars:
                    if elems(v) >= matrix_elems:
                        tracked.add(v)
        return charged

    return walk(jx, set(seed_vars))


def _check_j7(c: Contract, target: Target, jaxpr
              ) -> Tuple[List[Finding], Dict[str, object]]:
    if c.bin_arg is None:
        return [], {}
    _leaves, ranges = _flat_arg_leaves(target)
    lo, hi = ranges[c.bin_arg]
    if hi - lo != 1:
        return [_finding(
            c, "J7", f"bin_arg={c.bin_arg} is not a single-leaf array arg",
            "declare the positional index of the bin matrix itself")], {}
    jx = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    seed = jx.invars[lo]
    m_elems = 1
    for d in seed.aval.shape:
        m_elems *= int(d)
    m_bytes = _aval_bytes(seed.aval)
    got = bin_sweep_bytes(jaxpr, [seed], m_elems, m_bytes)
    sweeps = got / max(m_bytes, 1)
    findings = []
    if c.max_bin_sweeps is not None and sweeps > c.max_bin_sweeps:
        findings.append(_finding(
            c, "J7",
            f"estimated {sweeps:.2f} bin-matrix sweeps per round exceeds "
            f"the {c.max_bin_sweeps}-sweep contract budget",
            "a new full read of the bin matrix (or a matrix-scale copy "
            "of it) entered the round body — the megakernel's whole "
            "point is ONE sweep; route new bin consumers through the "
            "kernel or raise the budget consciously (docs/ANALYSIS.md "
            "J7)"))
    return findings, {"bin_sweeps": round(sweeps, 3)}


def _check_j6(c: Contract, jaxpr) -> Tuple[List[Finding], Dict[str, object]]:
    peak = peak_live_bytes(jaxpr)
    findings = []
    if peak > c.max_live_bytes:
        findings.append(_finding(
            c, "J6",
            f"estimated peak live set {peak} bytes exceeds the "
            f"{c.max_live_bytes}-byte contract budget",
            "an O(L*F*B)-class buffer joined the round state — shrink it "
            "or raise the budget consciously (the budget is what keeps "
            "the blowup failing CI instead of a v5e)"))
    return findings, {"peak_live_bytes": peak}


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def audit_contract(c: Contract) -> ContractResult:
    """Trace + lower one contract's executable and run J1-J6, applying
    the contract's waivers (mandatory reasons, like pragmas)."""
    target = c.build()
    traced = target.fn.trace(*target.args, **target.kwargs)
    jaxpr = traced.jaxpr
    # lower FROM the trace (AOT API) — fn.lower(...) would re-trace the
    # whole executable from scratch, doubling the audit's dominant cost
    lowered_text = traced.lower().as_text()

    raw: List[Finding] = []
    detail: Dict[str, object] = {"note": target.note}
    found = collect_collectives(jaxpr)
    j1, tokens = _check_j1(c, found)
    raw += j1
    detail["collectives"] = tokens
    detail["large_collectives"] = sum(
        1 for _n, _ax, nb in found if nb >= _LARGE_COLLECTIVE_BYTES)
    if found:
        detail["axis_bytes"] = axis_bytes(found)
    jdcn, ddcn = _check_dcn_bytes(c, found)
    raw += jdcn
    detail.update(ddcn)
    jfeat, dfeat = _check_feature_bytes(c, found)
    raw += jfeat
    detail.update(dfeat)
    j2, d2 = _check_j2(c, target, jaxpr, lowered_text)
    raw += j2
    detail.update(d2)
    raw += _check_j3(c, jaxpr)
    raw += _check_j4(c, jaxpr)
    j5, d5 = _check_j5(c, jaxpr)
    raw += j5
    detail.update(d5)
    j6, d6 = _check_j6(c, jaxpr)
    raw += j6
    detail.update(d6)
    j7, d7 = _check_j7(c, target, jaxpr)
    raw += j7
    detail.update(d7)

    # waiver hygiene first: unknown rules / missing reasons are P0 (never
    # waivable), mirroring the lint layer's pragma policy
    findings: List[Finding] = []
    waived: List[Tuple[Finding, str]] = []
    for rule, reason in c.waivers.items():
        if rule not in JAXPR_RULES:
            findings.append(_finding(
                c, "P0", f"waiver names unknown jaxpr rule {rule!r}",
                f"known rules: {', '.join(sorted(JAXPR_RULES))}"))
        elif not str(reason).strip():
            findings.append(_finding(
                c, "P0", f"waiver for {rule} has no reason",
                "every contract-level waiver must document why"))
    for f in raw:
        reason = c.waivers.get(f.rule, "")
        if f.rule in c.waivers and str(reason).strip():
            waived.append((f, str(reason)))
        else:
            findings.append(f)
    return ContractResult(c.name, findings, waived, detail)


def ledger_crosscheck(merges: Tuple[str, ...] = ("psum", "scatter")
                      ) -> Tuple[Dict[str, dict], List[Finding]]:
    """Run a tiny sharded windowed training per selected merge strategy
    and cross-check the runtime dispatch ledger against the auditor's
    collective count (utils/sanitizer.py::assert_ledger_agreement): one
    dispatch and zero blocking syncs per round on the ledger proves every
    audited collective rode INSIDE the donated round dispatch."""
    import numpy as np

    from ..binning import DatasetBinner
    from ..ops.split import SplitParams
    from ..parallel import data_parallel as dp
    from ..utils import sanitizer as _san
    from .contracts import _F, _L, _N, _TILE, audit_mesh

    rng = np.random.RandomState(0)
    X = rng.randn(_N, _F)
    y = X @ rng.randn(_F)
    binner = DatasetBinner.fit(X, max_bin=31)
    mesh = audit_mesh()
    sharded = dp.ShardedData(mesh, binner.transform(X).astype(np.int16),
                             np.asarray(binner.num_bins_per_feature),
                             np.asarray(binner.missing_bin_per_feature))
    grad = sharded.pad_rows(np.asarray(2 * y, np.float32))
    hess = sharded.pad_rows(np.ones(_N, np.float32))
    mask = sharded.pad_rows(np.ones(_N, bool), fill=False)
    sw = sharded.pad_rows(np.ones(_N, np.float32))
    fmask = np.ones(_F, bool)

    out: Dict[str, dict] = {}
    findings: List[Finding] = []
    for merge in merges:
        cname = f"windowed_round_sharded_{merge}"
        c = CONTRACTS[cname]
        stats: dict = {}
        tree, leaf = dp.grow_tree_windowed_data_parallel(
            sharded, grad, hess, mask, sw, fmask,
            num_leaves=_L, num_bins=32,
            params=SplitParams(min_data_in_leaf=5.0), leaf_tile=_TILE,
            use_pallas=False, merge=merge, stats=stats)
        import jax
        jax.block_until_ready(leaf)
        try:
            out[merge] = _san.assert_ledger_agreement(
                stats, collectives_per_round=len(c.collectives),
                what=f"sharded fused rounds (merge={merge})")
        except _san.BudgetError as e:
            findings.append(_finding(
                c, "J1", f"runtime ledger disagrees with the audited "
                         f"collective placement: {e}",
                "the collectives the auditor counted must all ride the "
                "single per-round dispatch — see docs/ANALYSIS.md "
                "'Jaxpr audit layer'"))
            out[merge] = {"error": str(e)}
    return out, findings


def run_jaxpr_audit(names: Optional[List[str]] = None,
                    runtime: bool = True) -> JaxprReport:
    """Audit the selected (default: all) registered contracts; with
    ``runtime`` also run the DispatchCounter ledger cross-check (executes
    a tiny sharded training — skipped automatically when the selection
    excludes the sharded contracts)."""
    selected = list(names) if names else sorted(CONTRACTS)
    unknown = [n for n in selected if n not in CONTRACTS]
    if unknown:
        raise ValueError(
            f"unknown contracts {unknown}; have {sorted(CONTRACTS)}")
    results = [audit_contract(CONTRACTS[n]) for n in selected]
    by_name = {r.name: r for r in results}
    fam = _check_family_spine(by_name)
    if fam:
        results.append(ContractResult("family-spine", fam, [], {}))
    ledger: Dict[str, dict] = {}
    # cross-check only the merge strategies the selection actually
    # audited — each one executes a tiny training
    merges = tuple(m for m in ("psum", "scatter")
                   if f"windowed_round_sharded_{m}" in selected)
    if runtime and merges:
        ledger, lf = ledger_crosscheck(merges)
        if lf:
            results.append(ContractResult("ledger-crosscheck", lf, [], {}))
    return JaxprReport(results=results, ledger=ledger)


def verdict(runtime: bool = False, exec_contracts: bool = True) -> dict:
    """Compact audit verdict for artifact embedding (bench.py): per-
    contract pass/fail/waiver summary — chip-session artifact rows carry
    proof the contracts held at trace time.  ``exec_contracts=False``
    additionally excludes contracts whose BUILDERS execute device code
    (the converted-predict toy booster) — on a chip those pay real
    remote compiles; the skipped names are listed so the verdict stays
    honest about its coverage."""
    try:
        names = sorted(CONTRACTS)
        skipped = []
        if not exec_contracts:
            skipped = [n for n in names if CONTRACTS[n].executes]
            names = [n for n in names if not CONTRACTS[n].executes]
        rep = run_jaxpr_audit(names, runtime=runtime)
    except Exception as e:  # noqa: BLE001 — artifact robustness first
        return {"ok": False, "error": f"{type(e).__name__}: {e}"[:300]}
    contracts = {}
    for r in rep.results:
        if r.findings:
            contracts[r.name] = f"FAILED:{len(r.findings)}"
        elif r.waived:
            contracts[r.name] = f"waived:{len(r.waived)}"
        else:
            contracts[r.name] = "ok"
    out = {
        "ok": rep.ok,
        "contracts": contracts,
        "findings": [f.format() for f in rep.findings][:20],
        "waivers": [[f.rule, f.message[:80], reason[:120]]
                    for f, reason in rep.waived],
        "ledger": rep.ledger,
    }
    # J7 sweep estimates ride the artifact next to the pass/fail rows —
    # a chip bench row carries the 3-vs-1 bin-sweep proof explicitly
    sweeps = {r.name: r.detail["bin_sweeps"] for r in rep.results
              if "bin_sweeps" in r.detail}
    if sweeps:
        out["bin_sweeps"] = sweeps
    # per-round DCN byte bills of the hierarchical contracts ride the
    # artifact too — a multislice bench row carries the cross-slice
    # budget proof next to the pass/fail rows
    dcn = {r.name: r.detail["dcn_bytes"] for r in rep.results
           if "dcn_bytes" in r.detail}
    if dcn:
        out["dcn_bytes"] = dcn
    # the full per-axis bills (row/feature/ici/dcn) of every collective-
    # bearing contract — a 2-D bench row shows where the round's traffic
    # lands on the mesh grid without re-running the audit
    per_axis = {r.name: r.detail["axis_bytes"] for r in rep.results
                if r.detail.get("axis_bytes")}
    if per_axis:
        out["axis_bytes"] = per_axis
    if skipped:
        out["skipped_exec_contracts"] = skipped
    return out
