"""Executable contracts for the jaxpr audit layer (docs/ANALYSIS.md
"Jaxpr audit layer").

A *contract* pins the traced-IR invariants of one flagship executable —
the properties the AST rules structurally cannot see (R1/R6/R13's
documented static limits: the shared ``_run_fused_rounds`` driver
receives its donated dispatch as a closure, so a second collective or a
dropped donation INSIDE the traced round body is invisible to source
lint).  Each contract bundles:

* a **builder** that constructs the executable and hermetic example
  arguments (CPU, no chip, no network; ShapeDtypeStructs wherever the
  trace does not need data, so building mostly never executes device
  code — the one exception is the converted-predict contract, which
  trains a 2-iteration toy booster to audit the REAL fused entry);
* the **declared invariants** the auditor (jaxpr_audit.py) checks on the
  traced jaxpr and lowered StableHLO:

  - ``collectives``: the exact ordered ``prim@axis`` sequence the
    executable may contain (J1).  Declaring the order pins cross-variant
    consistency: the psum and scatter merge variants share the same
    protocol spine (declared via ``spine``), so an accidental reorder or
    an extra collective in either fails the audit, not the chip session.
  - ``donated_args``: positional args whose buffers are donated; J2
    asserts every live donated leaf is actually consumable (and, where
    the platform lowers aliasing, actually aliased).
  - ``max_const_bytes``: J5's baked-constant ceiling for this trace.
  - ``max_live_bytes``: J6's conservative peak-live-bytes budget — an
    O(L*F*B) state blowup in the round body fails CI here before it
    fails allocation on a v5e.

Contracts are DECLARED NEXT TO the invariants they pin, in this module,
with contract-level **waivers** replacing line pragmas (a traced jaxpr
has no source line to hang a pragma on): ``waivers={"J6": "reason"}``
suppresses rule J6 for that contract, reason mandatory — a reasonless or
unknown-rule waiver is itself a P0 finding, exactly like the lint
layer's pragma hygiene.

Adding a contract::

    @contract(
        "my_executable",
        description="what it is and why its IR shape matters",
        collectives=("psum@data",),     # () = the body must be collective-free
        donated_args=(0,),
        max_live_bytes=1 << 22,
        family="my_family", spine=(0, 0),
    )
    def _build_my_executable() -> Target:
        ...
        return Target(fn=jitted, args=(...), kwargs=dict(static=...))

JAX is imported only inside builders, so importing this module (and the
``lightgbm_tpu.analysis`` package) stays device-state-free; the CLI sets
the loopback-device env BEFORE any builder runs.
"""

from __future__ import annotations

import dataclasses
import functools
import inspect
from typing import Callable, Dict, Mapping, Optional, Tuple

# shared hermetic shapes: every fixture sits far below one W-ladder rung
# (n < 8192 => the single rung W=8192 covers any round), so the windowed
# contracts trace the same one-rung executable the tier-1 budget pins run
_N, _F, _L, _TILE, _BINS = 512, 8, 7, 4, 32
_W = 8192  # the floor rung: _window_size(n // 2, n) for every n < 8192


@dataclasses.dataclass
class Target:
    """What a builder hands the auditor: the jitted callable plus the
    exact (positional args, static kwargs) to trace/lower it with."""

    fn: object
    args: tuple
    kwargs: dict
    note: str = ""


@dataclasses.dataclass(frozen=True)
class Contract:
    name: str
    description: str
    build: Callable[[], Target]
    collectives: Tuple[str, ...]
    donated_args: Tuple[int, ...]
    max_const_bytes: int
    max_live_bytes: int
    family: str
    spine: Tuple[int, int]  # (prefix, suffix) lengths shared family-wide
    waivers: Mapping[str, str]
    file: str
    line: int
    # True when the BUILDER executes device code (not just trace/lower) —
    # e.g. trains a toy model.  Cost-sensitive callers (bench.py on chip,
    # where every compile is a remote Mosaic compile) can exclude these.
    executes: bool = False
    # J7 (hbm-sweep-bound): positional index of the bin-matrix argument
    # and the per-round sweep budget the statically estimated bin-matrix
    # bytes-read must stay under.  None = J7 not pinned for this contract
    # (the sweep estimate is only meaningful at W≈N fixture shapes — see
    # the *_sweeps contracts below).
    bin_arg: Optional[int] = None
    max_bin_sweeps: Optional[float] = None
    # per-axis J1 accounting (the hierarchical merge's byte pin,
    # analogous to J7's sweep bound): total operand bytes of collectives
    # whose axes include the dcn axis must stay under this — ≤ top-k
    # histograms' worth per round.  None = no dcn traffic declared.
    dcn_max_bytes: Optional[int] = None
    # the feature-axis twin (the 2-D round's pin): ≤ the winner's
    # go/no-go row broadcast + election scalars per round.  None = no
    # feature-axis traffic declared.
    feature_max_bytes: Optional[int] = None


CONTRACTS: Dict[str, Contract] = {}


def contract(name: str, *, description: str,
             collectives: Tuple[str, ...] = (),
             donated_args: Tuple[int, ...] = (),
             max_const_bytes: int = 1 << 16,
             max_live_bytes: int,
             family: str = "",
             spine: Tuple[int, int] = (0, 0),
             waivers: Optional[Mapping[str, str]] = None,
             executes: bool = False,
             bin_arg: Optional[int] = None,
             max_bin_sweeps: Optional[float] = None,
             dcn_max_bytes: Optional[int] = None,
             feature_max_bytes: Optional[int] = None):
    """Register a contract; the decorated function is its builder."""

    def deco(build: Callable[[], Target]) -> Callable[[], Target]:
        if name in CONTRACTS:
            raise ValueError(f"duplicate contract {name!r}")
        frame = inspect.stack()[1]
        CONTRACTS[name] = Contract(
            name=name, description=description, build=build,
            collectives=tuple(collectives),
            donated_args=tuple(donated_args),
            max_const_bytes=max_const_bytes,
            max_live_bytes=max_live_bytes, family=family, spine=spine,
            waivers=dict(waivers or {}), file=frame.filename,
            line=frame.lineno, executes=executes,
            bin_arg=bin_arg, max_bin_sweeps=max_bin_sweeps,
            dcn_max_bytes=dcn_max_bytes,
            feature_max_bytes=feature_max_bytes)
        return build

    return deco


# ---------------------------------------------------------------------------
# shared builders
# ---------------------------------------------------------------------------

def _sds(shape, dtype):
    import jax
    return jax.ShapeDtypeStruct(shape, dtype)


def _split_params():
    from ..ops.split import SplitParams
    return SplitParams(min_data_in_leaf=5.0)


def _round_common(n_leaves=_L, bins=_BINS, tile=_TILE):
    return dict(num_leaves=n_leaves, num_bins=bins, params=_split_params(),
                leaf_tile=tile)


def _single_state(quantize_bins: int, n=_N, f=_F, common=None):
    """WState avals for the single-device round via eval_shape over
    ``_w_init`` — abstract, nothing executes."""
    import functools as ft

    import jax
    import jax.numpy as jnp

    from ..ops import treegrow_windowed as tw

    row = lambda dt: _sds((n,), dt)  # noqa: E731
    pf = _sds((f,), jnp.int32)
    out = jax.eval_shape(
        ft.partial(tw._w_init.__wrapped__, use_pallas=False,
                   quantize_bins=quantize_bins, hist_precision="f32",
                   stochastic_rounding=False, **(common or _round_common())),
        _sds((f, n), jnp.int16), row(jnp.float32), row(jnp.float32),
        row(jnp.bool_), row(jnp.float32), pf, pf, _sds((f,), jnp.bool_),
        None, None, None)
    return out[0]


def _windowed_single_target(quantize_bins: int, n=_N, f=_F, tile=_TILE,
                            megakernel: bool = False) -> Target:
    import jax.numpy as jnp

    from ..ops import treegrow_windowed as tw

    common = _round_common(tile=tile)
    row = lambda dt: _sds((n,), dt)  # noqa: E731
    pf = _sds((f,), jnp.int32)
    q = bool(quantize_bins)
    args = (
        _single_state(quantize_bins, n, f, common), _sds((f, n), jnp.int16),
        row(jnp.float32), row(jnp.float32),
        row(jnp.int8) if q else None, row(jnp.int8) if q else None,
        _sds((3,), jnp.float32) if q else None,
        row(jnp.bool_), pf, pf, _sds((f,), jnp.bool_),
        None, None, None, None, None, None,
    )
    kw = dict(max_depth=-1, W=_W, use_pallas=False,
              quantize_bins=quantize_bins, hist_precision="f32",
              megakernel=megakernel, mk_interpret=megakernel, **common)
    return Target(tw._round_fused, args, kw,
                  note=("megakernel round (interpret-mode Pallas call in "
                        "the trace)" if megakernel else
                        "single-device fused round (CPU trace: XLA "
                        "histogram fallback, Pallas off)"))


def audit_mesh():
    """The loopback mesh the sharded contracts trace over: up to 4 host
    devices (tests force 8 via conftest's XLA_FLAGS; the CLI sets the
    same flag before jax loads).  On a single-device interpreter the
    collectives still trace identically — axis size only changes the
    lowering, not the jaxpr."""
    import jax

    from ..parallel.mesh import make_mesh
    return make_mesh(min(4, len(jax.devices())))


def _windowed_sharded_target(merge: str, megakernel: bool = False) -> Target:
    import jax
    import jax.numpy as jnp

    from ..parallel import data_parallel as dp
    from ..parallel.mesh import data_axis_size

    mesh = audit_mesh()
    n_dev = data_axis_size(mesh)
    f_pad = (-(-_F // n_dev) * n_dev) if merge == "scatter" else _F
    row = lambda dt: _sds((_N,), dt)  # noqa: E731
    bt = _sds((f_pad, _N), jnp.int16)
    pf = _sds((f_pad,), jnp.int32)
    fm = _sds((f_pad,), jnp.bool_)
    init_statics = tuple(sorted(dict(
        _round_common(), use_pallas=False, quantize_bins=0,
        hist_precision="f32", stochastic_rounding=False).items()))
    init_fn = dp._windowed_init_sharded(mesh, merge, (), init_statics)
    state = jax.eval_shape(init_fn, bt, row(jnp.float32), row(jnp.float32),
                           row(jnp.bool_), row(jnp.float32), pf, pf, fm)[0]
    round_statics = tuple(sorted(dict(
        _round_common(), max_depth=-1, use_pallas=False, quantize_bins=0,
        hist_precision="f32", has_cat=False,
        pallas_partition=False, megakernel=megakernel,
        mk_interpret=megakernel).items()))
    fn = dp._windowed_round_sharded(mesh, _W, merge, (), round_statics)
    args = (state, bt, row(jnp.float32), row(jnp.float32), row(jnp.bool_),
            pf, pf, fm)
    return Target(fn, args, {},
                  note=f"jit(shard_map) fused round, merge={merge!r}, "
                       f"{n_dev}-device loopback mesh"
                       + (", megakernel round body" if megakernel else ""))


# the sharded round's protocol spine, identical across merge variants
# (J1 family check): window verification + info-vector merge...
_ROUND_PREFIX = (
    "psum@data",   # global left counts (window-child election)
    "psum@data",   # global segment lengths (same election)
    "pmin@data",   # info: ok — one rank breaching skips the round fleet-wide
    "pmax@data",   # info: total — corrected W must cover the worst rank
)
# ...and the two trailing info merges after the split search
_ROUND_SUFFIX = (
    "pmax@data",   # info: whint — laddered W covers the worst rank
    "pmin@data",   # info: finite — rank-consistent non-finite guard
)

# the owned-feature winner election (_merge_best + _split_tables) between
# the scatter merge and the info suffix: globalize the feature index,
# elect by gain, psum-mask-broadcast every BestSplit field from the owner
_SCATTER_ELECTION = (
    "axis_index@data",             # _split_tables: this rank's F/R offset
    "axis_index@data",             # _merge_best: owner election index
    "pmax@data", "pmin@data",      # gain max, lowest-rank tie-break
) + ("psum@data",) * 12            # one masked broadcast per BestSplit field


# ---------------------------------------------------------------------------
# windowed fused round (ops/treegrow_windowed.py, parallel/data_parallel.py)
# ---------------------------------------------------------------------------

@contract(
    "windowed_round_float",
    description="single-device fused windowed round, float histograms — "
                "the one-dispatch donated executable tests/test_retrace.py "
                "budget-pins; its body must stay collective-free, f64-free, "
                "callback-free, with every donated WState buffer consumable",
    collectives=(),
    donated_args=(0,),
    # measured peak ≈ 4.03 MB at the 512x8/L7/B32 fixture shape (the CPU
    # fallback's vmapped window histogram dominates); 10 MB keeps ~2.5x
    # headroom while still catching an O(L*F*B) state duplication
    max_live_bytes=10 << 20,
    family="windowed_single",
)
def _build_windowed_round_float() -> Target:
    return _windowed_single_target(0)


@contract(
    "windowed_round_quantized",
    description="single-device fused windowed round, int8-quantized config "
                "(CPU trace: dequantized fallback histograms) — the wide-"
                "regime default; same contract as the float round",
    collectives=(),
    donated_args=(0,),
    max_live_bytes=10 << 20,
    family="windowed_single",
)
def _build_windowed_round_quantized() -> Target:
    return _windowed_single_target(16)


@contract(
    "windowed_round_sharded_psum",
    description="SPMD fused windowed round over the ICI mesh, merge='psum' "
                "(tree_learner=data): exactly ONE large in-dispatch "
                "collective — the leaf-histogram psum — plus the declared "
                "scalar protocol merges, all on the data axis, in order",
    collectives=_ROUND_PREFIX + ("psum@data",) + _ROUND_SUFFIX,
    donated_args=(0,),
    max_live_bytes=10 << 20,  # sharded measured ≈ 4.09 MB
    family="windowed_sharded",
    spine=(len(_ROUND_PREFIX), len(_ROUND_SUFFIX)),
)
def _build_windowed_round_sharded_psum() -> Target:
    return _windowed_sharded_target("psum")


@contract(
    "windowed_round_sharded_scatter",
    description="SPMD fused windowed round, merge='scatter' "
                "(tree_learner=voting): ONE large in-dispatch collective — "
                "the psum_scatter histogram merge — then the owned-feature "
                "winner election (all small-operand), same protocol spine "
                "as the psum variant",
    collectives=(_ROUND_PREFIX + ("psum_scatter@data",)
                 + _SCATTER_ELECTION + _ROUND_SUFFIX),
    donated_args=(0,),
    max_live_bytes=10 << 20,  # sharded measured ≈ 4.09 MB
    family="windowed_sharded",
    spine=(len(_ROUND_PREFIX), len(_ROUND_SUFFIX)),
)
def _build_windowed_round_sharded_scatter() -> Target:
    return _windowed_sharded_target("scatter")


# ---------------------------------------------------------------------------
# hierarchical two-level merge (parallel/hierarchy.py) — the multi-slice
# round.  The intra-slice (ici) sequence must equal the legacy sharded
# round's (tests/test_jaxpr_audit.py asserts the axis-mapped identity),
# and the dcn-axis byte bill is pinned at ≤ top-k histograms' worth.
# ---------------------------------------------------------------------------

_HIER_TOPK = 4  # the fixture election width (k < F: a real sub-election)

# scalar protocol merges span BOTH axes (window election + info vector
# are global agreements); the histogram merge stays per-slice on ici
_HIER_PREFIX = tuple(t.replace("@data", "@ici,dcn") for t in _ROUND_PREFIX)
_HIER_SUFFIX = tuple(t.replace("@data", "@ici,dcn") for t in _ROUND_SUFFIX)
# the dcn election: k gain scalars + k feature ids all_gathered, then the
# elected k features' histogram columns psummed — the ONLY
# histogram-shaped dcn operand (jaxlint R17's clean shape)
_HIER_ELECTION = ("all_gather@dcn", "all_gather@dcn", "psum@dcn")
_HIER_SCATTER_ELECTION = tuple(
    t.replace("@data", "@ici") for t in _SCATTER_ELECTION)

# the fixture's per-round dcn bill: C=2*tile candidates x 3 channels x
# k features x B bins x 4 bytes for the elected-histogram psum, plus the
# two (S, C, k) vote all_gathers and the 4-byte both-axes scalars — the
# "top-k histograms' worth" promise, with ~1 KB scalar slack
_HIER_DCN_BUDGET = 2 * _TILE * 3 * _HIER_TOPK * _BINS * 4 + 1024


def _audit_mesh_hier():
    """Loopback nested (dcn, ici) mesh: 2 slices x 2 ranks on the
    virtual 8-device host (axis size only changes the lowering, not the
    jaxpr — see audit_mesh)."""
    import jax

    from ..parallel.mesh import make_mesh_hierarchical
    n = len(jax.devices())
    if n >= 4:
        return make_mesh_hierarchical(2, 2)
    return make_mesh_hierarchical(min(n, 2), 1)


def _windowed_hier_target(merge: str) -> Target:
    import jax
    import jax.numpy as jnp

    from ..parallel import hierarchy as hy
    from ..parallel.mesh import slice_axis_sizes

    mesh = _audit_mesh_hier()
    _, n_ici = slice_axis_sizes(mesh)
    f_pad = (-(-_F // n_ici) * n_ici) if merge == "scatter" else _F
    row = lambda dt: _sds((_N,), dt)  # noqa: E731
    bt = _sds((f_pad, _N), jnp.int16)
    pf = _sds((f_pad,), jnp.int32)
    fm = _sds((f_pad,), jnp.bool_)
    init_statics = tuple(sorted(dict(
        _round_common(), use_pallas=False, quantize_bins=0,
        hist_precision="f32", stochastic_rounding=False).items()))
    init_fn = hy._windowed_init_hier(mesh, merge, _HIER_TOPK, (),
                                     init_statics)
    state = jax.eval_shape(init_fn, bt, row(jnp.float32), row(jnp.float32),
                           row(jnp.bool_), row(jnp.float32), pf, pf, fm)[0]
    round_statics = tuple(sorted(dict(
        _round_common(), max_depth=-1, use_pallas=False, quantize_bins=0,
        hist_precision="f32", has_cat=False, pallas_partition=False,
        megakernel=False, mk_interpret=False).items()))
    fn = hy._windowed_round_hier(mesh, _W, merge, _HIER_TOPK, (),
                                 round_statics)
    args = (state, bt, row(jnp.float32), row(jnp.float32), row(jnp.bool_),
            pf, pf, fm)
    return Target(fn, args, {},
                  note=f"jit(shard_map) hierarchical round, intra-slice "
                       f"merge={merge!r}, top_k={_HIER_TOPK}, nested "
                       f"{mesh.devices.shape} loopback mesh")


@contract(
    "windowed_round_hierarchical_psum",
    description="two-level fused windowed round over the nested "
                "(dcn, ici) mesh, intra-slice merge='psum' "
                "(tree_learner=data x num_slices>1): the slice-local "
                "histogram psum rides ici UNCHANGED vs the single-level "
                "round, the scalar protocol spans both axes, and the "
                "only histogram-shaped dcn operand is the elected "
                "top-k feature exchange — byte bill pinned",
    collectives=(_HIER_PREFIX + ("psum@ici",) + _HIER_ELECTION
                 + _HIER_SUFFIX),
    donated_args=(0,),
    max_live_bytes=10 << 20,  # measured ≈ 4.15 MB at the fixture shape
    family="windowed_hierarchical",
    spine=(len(_HIER_PREFIX), len(_HIER_SUFFIX)),
    dcn_max_bytes=_HIER_DCN_BUDGET,
)
def _build_windowed_round_hierarchical_psum() -> Target:
    return _windowed_hier_target("psum")


@contract(
    "windowed_round_hierarchical_voting",
    description="two-level fused windowed round, intra-slice "
                "merge='scatter' (tree_learner=voting x num_slices>1): "
                "psum_scatter + owned-feature election over ici exactly "
                "as the single-level scatter round, the dcn top-k "
                "exchange inside each rank's owned feature block — the "
                "full PV-Tree route, byte bill pinned",
    collectives=(_HIER_PREFIX + ("psum_scatter@ici", "axis_index@ici")
                 + _HIER_ELECTION + _HIER_SCATTER_ELECTION[1:]
                 + _HIER_SUFFIX),
    donated_args=(0,),
    max_live_bytes=10 << 20,  # measured ≈ 4.13 MB at the fixture shape
    family="windowed_hierarchical",
    spine=(len(_HIER_PREFIX), len(_HIER_SUFFIX)),
    dcn_max_bytes=_HIER_DCN_BUDGET,
)
def _build_windowed_round_hierarchical_voting() -> Target:
    return _windowed_hier_target("scatter")


# ---------------------------------------------------------------------------
# 2-D (feature x row) sharded round (parallel/feature2d.py) — the wide-F
# regime.  The histogram phase must cross the feature axis with ZERO
# collectives (the tile's histograms are complete for the owned block by
# layout); the feature axis carries only the winner's go/no-go row
# broadcast and the owned-feature election, byte-billed and pinned.
# ---------------------------------------------------------------------------

def _audit_mesh_2d():
    """Loopback 2-D (row, feature) mesh: 2 x 2 on the virtual 8-device
    host (axis size only changes the lowering, not the jaxpr — see
    audit_mesh)."""
    import jax

    from ..parallel.mesh import make_mesh_2d
    n = len(jax.devices())
    if n >= 4:
        return make_mesh_2d(2, 2)
    return make_mesh_2d(1, min(n, 2))


def _windowed_2d_target(quantize_bins: int) -> Target:
    import jax
    import jax.numpy as jnp

    from ..parallel import feature2d as f2d

    mesh = _audit_mesh_2d()
    q = bool(quantize_bins)
    row = lambda dt: _sds((_N,), dt)  # noqa: E731
    bt = _sds((_F, _N), jnp.int16)  # _F divides d_f=2: no dead padding
    pf = _sds((_F,), jnp.int32)
    fm = _sds((_F,), jnp.bool_)
    init_statics = tuple(sorted(dict(
        _round_common(), use_pallas=False, quantize_bins=quantize_bins,
        hist_precision="f32", stochastic_rounding=False).items()))
    init_names = ("quant_key",) if q else ()
    init_fn = f2d._windowed_init_2d(mesh, init_names, init_statics)
    init_args = (bt, row(jnp.float32), row(jnp.float32), row(jnp.bool_),
                 row(jnp.float32), pf, pf, fm)
    if q:
        init_args = init_args + (_sds((2,), jnp.uint32),)
    state = jax.eval_shape(init_fn, *init_args)[0]
    round_statics = tuple(sorted(dict(
        _round_common(), max_depth=-1, use_pallas=False,
        quantize_bins=quantize_bins, hist_precision="f32", has_cat=False,
        pallas_partition=False, megakernel=False,
        mk_interpret=False).items()))
    names = ("gq", "hq", "quant_scale") if q else ()
    fn = f2d._windowed_round_2d(mesh, _W, names, round_statics)
    args = (state, bt, row(jnp.float32), row(jnp.float32), row(jnp.bool_),
            pf, pf, fm)
    if q:
        args = args + (row(jnp.int8), row(jnp.int8), _sds((3,), jnp.float32))
    d_r, d_f = mesh.shape["data"], mesh.shape["feature"]
    return Target(fn, args, {},
                  note=f"jit(shard_map) 2-D fused round, "
                       f"{d_r}x{d_f} (row x feature) loopback mesh"
                       + (", int8-quantized config" if q else ""))


# the winner's row decisions — computable only on the owner's feature
# block — broadcast at round start, BEFORE the partition movement: the
# round's only feature-axis data exchange
_2D_DECIDE = ("axis_index@feature", "psum@feature")
# the protocol spine: row-domain sums stay on the row axis alone (a
# feature-axis sum would over-count the replicated rows d_f times);
# idempotent info merges span both axes
_2D_PREFIX = _2D_DECIDE + (
    "psum@data",           # global left counts (window-child election)
    "psum@data",           # global segment lengths (same election)
    "pmin@data,feature",   # info: ok — idempotent, spans the full mesh
    "pmax@data,feature",   # info: total
)
_2D_SUFFIX = (
    "pmax@data,feature",   # info: whint
    "pmin@data,feature",   # info: finite
)
# the owned-feature winner election (the scatter merge's machinery with
# the FEATURE axis as the owning axis): globalize the block offset,
# elect by gain, psum-mask-broadcast every BestSplit field from the owner
_2D_ELECTION = (
    "axis_index@feature",          # _split_tables: this block's F offset
    "axis_index@feature",          # _merge_best: owner election index
    "pmax@feature", "pmin@feature",  # gain max, lowest-block tie-break
) + ("psum@feature",) * 12         # one masked broadcast per field

# the per-round feature-axis byte bill: the go/no-go row broadcast
# ((N_loc,) i32, worst case d_r=1) + the election's per-leaf broadcast +
# scalar slack — a full histogram merge (3*F*B*4 per leaf pair) cannot fit
_2D_FEATURE_BUDGET = 2 * _N * 4 + 1024


@contract(
    "windowed_round_2d_float",
    description="SPMD fused windowed round over the 2-D (feature x row) "
                "mesh, float histograms: the histogram phase is the row "
                "psum ALONE — zero feature-axis collectives (the owned "
                "block's histograms are complete by layout) — then the "
                "owned-feature election and the winner's row-decision "
                "broadcast, the only feature-axis traffic, byte-billed",
    collectives=_2D_PREFIX + ("psum@data",) + _2D_ELECTION + _2D_SUFFIX,
    donated_args=(0,),
    max_live_bytes=10 << 20,  # measured ≈ 4.1 MB at the fixture shape
    family="windowed_2d",
    spine=(len(_2D_PREFIX), len(_2D_SUFFIX)),
    feature_max_bytes=_2D_FEATURE_BUDGET,
)
def _build_windowed_round_2d_float() -> Target:
    return _windowed_2d_target(0)


@contract(
    "windowed_round_2d_quantized",
    description="SPMD fused windowed round over the 2-D mesh, int8-"
                "quantized config (CPU trace: dequantized fallback "
                "histograms) — the wide-F regime default; same sequence, "
                "same feature-axis byte bill as the float round",
    collectives=_2D_PREFIX + ("psum@data",) + _2D_ELECTION + _2D_SUFFIX,
    donated_args=(0,),
    max_live_bytes=10 << 20,
    family="windowed_2d",
    spine=(len(_2D_PREFIX), len(_2D_SUFFIX)),
    feature_max_bytes=_2D_FEATURE_BUDGET,
)
def _build_windowed_round_2d_quantized() -> Target:
    return _windowed_2d_target(16)


# ---------------------------------------------------------------------------
# round megakernel (ops/round_pallas.py) + J7 sweep pins
# ---------------------------------------------------------------------------
# J7's sweep estimate is shape-relative (the window gather reads W
# columns), so the sweep-pinned contracts trace at n == _W == 8192 (still
# exactly ONE ladder rung) with f=64/tile=2 to keep the decisions-gather
# epsilon (tile/f) small: the legacy round's three window-scale reads
# document as 3 + tile/f ≈ 3.03, the megakernel's single kernel charge as
# 1 + tile/f ≈ 1.03.

_NS, _FS, _TILES = 8192, 64, 2  # the W=N sweep-pin fixture shape


@contract(
    "windowed_round_megakernel",
    description="single-device MEGAKERNEL round (ops/round_pallas.py, "
                "interpret-mode Pallas call in the trace): partition + "
                "one-sweep window histogram + on-core per-feature gain "
                "reduction in ONE kernel — collective-free, donated, and "
                "<= 1 bin-matrix sweep (+ the tile/f decisions-gather "
                "epsilon) by J7's static estimate",
    collectives=(),
    donated_args=(0,),
    # the kernel's ref plumbing + the vmapped on-core gain planes at the
    # 8192x64 fixture measure ≈27 MB peak-live; 64 MB headroom still
    # catches an O(L*F*B) state duplication
    max_live_bytes=64 << 20,
    family="windowed_single",
    bin_arg=1,
    max_bin_sweeps=1.1,
)
def _build_windowed_round_megakernel() -> Target:
    return _windowed_single_target(0, n=_NS, f=_FS, tile=_TILES,
                                   megakernel=True)


@contract(
    "windowed_round_three_pass_sweeps",
    description="the LEGACY three-pass round at the same W=N fixture — "
                "J7 documents its three bin-matrix sweeps (window gather "
                "+ transpose of the materialized copy + the histogram's "
                "int cast, ~3 + tile/f) next to the megakernel's one; "
                "this contract is the baseline the 3->1 claim is pinned "
                "against",
    collectives=(),
    donated_args=(0,),
    max_live_bytes=64 << 20,  # the (W, F) window copy + scatter payloads
    family="windowed_single",
    bin_arg=1,
    max_bin_sweeps=3.2,
)
def _build_windowed_round_three_pass_sweeps() -> Target:
    return _windowed_single_target(0, n=_NS, f=_FS, tile=_TILES)


@contract(
    "windowed_round_sharded_megakernel_psum",
    description="SPMD megakernel round, merge='psum': the kernel fuses "
                "each rank's partition + window histogram, and the round "
                "keeps the IDENTICAL collective protocol as the three-"
                "pass sharded round (windowed_round_sharded_psum) — the "
                "single large in-dispatch histogram merge UNCHANGED, "
                "pinned by J1's exact-sequence + family-spine checks",
    collectives=_ROUND_PREFIX + ("psum@data",) + _ROUND_SUFFIX,
    donated_args=(0,),
    max_live_bytes=10 << 20,
    family="windowed_sharded",
    spine=(len(_ROUND_PREFIX), len(_ROUND_SUFFIX)),
)
def _build_windowed_round_sharded_megakernel_psum() -> Target:
    return _windowed_sharded_target("psum", megakernel=True)


# ---------------------------------------------------------------------------
# warm predict entries (ops/predict.py, models/gbdt.py)
# ---------------------------------------------------------------------------

_PN, _PF, _PT, _PL = 128, 8, 8, 8  # bucket rows, features, trees, leaves


def _packed_sds():
    import jax.numpy as jnp
    m = _PL - 1
    return dict(
        split_feature=_sds((_PT, m), jnp.int32),
        threshold=_sds((_PT, m), jnp.float32),
        default_left=_sds((_PT, m), jnp.bool_),
        missing_type=_sds((_PT, m), jnp.int32),
        left_child=_sds((_PT, m), jnp.int32),
        right_child=_sds((_PT, m), jnp.int32),
        num_leaves=_sds((_PT,), jnp.int32),
        leaf_value=_sds((_PT, _PL), jnp.float32),
    )


@contract(
    "predict_warm_single",
    description="warm single-class predict traversal (predict_raw_values) "
                "on a bucket-padded batch with an active mask — the 1-"
                "dispatch serving entry tests/test_predict_budget.py pins",
    collectives=(),
    # measured peak ≈ 44 KB at the 128x8/T8 fixture; 1 MB bounds a
    # traversal that starts materializing per-(tree,row,node) temporaries
    max_live_bytes=1 << 20,
)
def _build_predict_warm_single() -> Target:
    import jax.numpy as jnp

    from ..ops import predict as predict_ops
    s = _packed_sds()
    args = (_sds((_PN, _PF), jnp.float32), s["split_feature"],
            s["threshold"], s["default_left"], s["missing_type"],
            s["left_child"], s["right_child"], s["num_leaves"],
            s["leaf_value"])
    return Target(predict_ops.predict_raw_values, args,
                  dict(active=_sds((_PN,), jnp.bool_)),
                  note="non-categorical pack (the cat variant adds bitset "
                       "gathers, same contract class)")


@contract(
    "predict_warm_multiclass",
    description="warm multiclass predict (predict_raw_multiclass, k=4): "
                "all classes reduced in the SAME single dispatch via the "
                "class-reshaped sum — no per-class loop may reappear",
    collectives=(),
    max_live_bytes=1 << 20,
)
def _build_predict_warm_multiclass() -> Target:
    import jax.numpy as jnp

    from ..ops import predict as predict_ops
    s = _packed_sds()
    args = (_sds((_PN, _PF), jnp.float32), s["split_feature"],
            s["threshold"], s["default_left"], s["missing_type"],
            s["left_child"], s["right_child"], s["num_leaves"],
            s["leaf_value"])
    return Target(predict_ops.predict_raw_multiclass, args,
                  dict(active=_sds((_PN,), jnp.bool_), k=4))


@functools.lru_cache(maxsize=1)
def _tiny_booster():
    """A 2-iteration toy binary booster: the ONLY contract builder that
    executes device code, because the fused converted-predict entry is an
    instance-cached jit closing over the model's real objective
    (models/gbdt.py::_get_convert_entry) — auditing a replica would let
    the real entry drift."""
    import numpy as np

    import lightgbm_tpu as lgb

    rng = np.random.RandomState(0)
    X = rng.randn(192, _PF)
    y = (X[:, 0] + 0.25 * X[:, 1] > 0).astype(np.float64)
    d = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 4,
                              "min_data_in_leaf": 5, "verbosity": -1},
                      train_set=d)
    for _ in range(2):
        bst.update()
    return bst._gbdt


@contract(
    "predict_warm_converted",
    description="fused converted predict (traversal + objective."
                "convert_output in ONE trace, models/gbdt.py::"
                "_get_convert_entry) — the round-12 single-dispatch entry; "
                "audited on a real 2-iteration binary booster",
    collectives=(),
    max_live_bytes=1 << 20,
    executes=True,  # the builder trains the toy booster
)
def _build_predict_warm_converted() -> Target:
    import jax.numpy as jnp

    g = _tiny_booster()
    s = g._packed(0, -1)
    run = g._get_convert_entry()
    args = (_sds((_PN, _PF), jnp.float32), s["split_feature"],
            s["threshold"], s["default_left"], s["missing_type"],
            s["left_child"], s["right_child"], s["num_leaves"],
            s["leaf_value"], s.get("is_cat"), s.get("cat_base"),
            s.get("cat_nwords"), s.get("cat_words"),
            _sds((_PN,), jnp.bool_))
    return Target(run, args, dict(k=1))


@contract(
    "predict_coalesced_bucket",
    description="the serving runtime's coalesced batch dispatch "
                "(lightgbm_tpu/serve/runtime.py -> GBDT.predict_coalesced): "
                "K concurrent requests packed into one bucket rung must "
                "dispatch the SAME traced executable family as warm "
                "single-caller predict — the fn is resolved through the "
                "runtime's own selector (serve.runtime.audit_dispatch_fn "
                "-> GBDT._coalesced_raw_fn), so a serve-owned second "
                "entry, a collective, or an in-trace transfer appearing "
                "in the serving loop fails the audit statically",
    collectives=(),
    max_live_bytes=1 << 20,
)
def _build_predict_coalesced_bucket() -> Target:
    import jax.numpy as jnp

    from ..serve.runtime import audit_dispatch_fn
    s = _packed_sds()
    fn = audit_dispatch_fn(1)
    args = (_sds((_PN, _PF), jnp.float32), s["split_feature"],
            s["threshold"], s["default_left"], s["missing_type"],
            s["left_child"], s["right_child"], s["num_leaves"],
            s["leaf_value"])
    return Target(fn, args, dict(active=_sds((_PN,), jnp.bool_)),
                  note="same fixture shape as predict_warm_single — the "
                       "coalesced dispatch IS that executable family by "
                       "construction, and this contract pins it")


@contract(
    "continual_refit_leaves",
    description="the continual runner's leaf-refit dispatch (lightgbm_tpu/"
                "continual/refit.py::make_refit_entry): the stacked leaf-"
                "index traversal + per-tree gradient/segment-sum/renewal "
                "scan + score accumulation, fused into ONE donated "
                "executable — the update that runs at ingest cadence "
                "beside live serving, so it must stay collective-free, "
                "transfer-free, and consume its donated leaf table (the "
                "caller uploads a FRESH table, never the serving pack's "
                "buffer).  Resolved through the runtime's own builder "
                "(continual.refit.audit_refit_fn), so a refit path that "
                "grew a second executable family fails here statically",
    collectives=(),
    donated_args=(0,),
    # the scan carries (N,) score + per-tree (L,) sums; measured peak is
    # well under 1 MB at the 128x8/T8/L8 fixture — 2 MB headroom catches
    # an accidental (T, N) or (N, L) materialization
    max_live_bytes=2 << 20,
)
def _build_continual_refit_leaves() -> Target:
    import jax.numpy as jnp

    from ..continual.refit import audit_refit_fn

    s = _packed_sds()
    fn = audit_refit_fn()
    args = (s["leaf_value"],                    # donated (T, L) leaf table
            _sds((_PT,), jnp.float32),          # per-tree shrinkage
            _sds((_PN, _PF), jnp.float32),      # bucket-padded window rows
            s["split_feature"], s["threshold"], s["default_left"],
            s["missing_type"], s["left_child"], s["right_child"],
            s["num_leaves"],
            None, None, None, None,             # non-categorical pack
            _sds((_PN,), jnp.float32),          # padded labels
            _sds((_PN,), jnp.bool_))            # active mask
    return Target(fn, args, {},
                  note="regression objective (the binary/other single-"
                       "output entries share the same trace shape: "
                       "gradients are elementwise over the score)")


# ---------------------------------------------------------------------------
# fleet round (ops/treegrow_fleet.py)
# ---------------------------------------------------------------------------

_FB = 4  # fleet lanes in the fixture — small, but enough that a
# superlinear state duplication (O(B^2) broadcast in the vmapped body)
# overshoots the linear budget below


@contract(
    "fleet_round_batched",
    description="the vmapped fleet round (B independent boosters, one "
                "donated dispatch): the solo round body lifted over a "
                "leading model axis plus the in-dispatch (B,5)->(5,) "
                "info fold — vmap must add ZERO collectives vs. the "
                "single-model round (J1), donation consumed on the "
                "(B, ...) stacked state (J2), peak-live LINEAR in B at "
                "the fixture shape (J6: B x the solo budget)",
    collectives=(),
    donated_args=(0,),
    # the solo float round measures ~4.03 MB at this fixture under its
    # 10 MB budget; linear-in-B means the fleet stays under _FB x that —
    # an accidental O(B^2) buffer (e.g. a cross-lane broadcast in the
    # histogram fallback) fails HERE, before it fails allocation at
    # B=4096 on chip
    max_live_bytes=_FB * (10 << 20),
    family="fleet",
)
def _build_fleet_round_batched() -> Target:
    import jax
    import jax.numpy as jnp

    from ..ops import treegrow_fleet as tf

    common = _round_common()
    solo = _single_state(0, _N, _F, common)
    stacked = jax.tree_util.tree_map(
        lambda s: _sds((_FB,) + tuple(s.shape), s.dtype), solo)
    row = lambda dt: _sds((_FB, _N), dt)  # noqa: E731
    pf = _sds((_F,), jnp.int32)
    args = (stacked, _sds((_F, _N), jnp.int16),
            row(jnp.float32), row(jnp.float32),
            None, None, None,
            row(jnp.bool_), pf, pf, _sds((_F,), jnp.bool_))
    kw = dict(max_depth=-1, W=_W, use_pallas=False, quantize_bins=0,
              hist_precision="f32", pallas_partition=False, **common)
    return Target(tf._fleet_round, args, kw,
                  note="B=4 float fleet round (CPU trace: XLA histogram "
                       "fallback; the quantized/Pallas lanes share the "
                       "solo contracts' variant coverage)")


# ---------------------------------------------------------------------------
# spill grower chunk steps (ops/treegrow_ooc.py)
# ---------------------------------------------------------------------------

_CN, _CC = 4096, 1024  # padded resident rows, chunk rows (both < 8192)


@contract(
    "ooc_root_chunk",
    description="spill-grower root-pass chunk step (_root_chunk_step): the "
                "donated histogram fold plus in-jit mask/slice — the one "
                "accounted dispatch per chunk the OOC docstring promises",
    collectives=(),
    donated_args=(0,),
    # measured peak ≈ 0.5 MB (chunk payload broadcast); 2 MB headroom
    max_live_bytes=2 << 20,
)
def _build_ooc_root_chunk() -> Target:
    import jax.numpy as jnp

    from ..ops.treegrow_ooc import _root_chunk_step
    args = (_sds((3, _F, _BINS), jnp.float32), _sds((_CC, _F), jnp.int16),
            _sds((), jnp.int32), _sds((_CC,), jnp.bool_),
            _sds((_CN,), jnp.float32), _sds((_CN,), jnp.float32),
            _sds((_CN,), jnp.bool_))
    return Target(_root_chunk_step, args, dict(num_bins=_BINS))


@contract(
    "ooc_split_chunk",
    description="spill-grower split-sweep chunk step (_split_chunk_step): "
                "fused partition + small-child histogram fold, leaf ids "
                "AND the accumulator donated",
    collectives=(),
    donated_args=(0, 1),
    max_live_bytes=2 << 20,
)
def _build_ooc_split_chunk() -> Target:
    import jax.numpy as jnp

    from ..ops.treegrow_ooc import _split_chunk_step
    sel = dict(best_leaf=_sds((), jnp.int32), feature=_sds((), jnp.int32),
               threshold_bin=_sds((), jnp.int32),
               default_left=_sds((), jnp.bool_), is_cat=_sds((), jnp.bool_),
               cat_mask=_sds((_BINS,), jnp.bool_),
               new_leaf=_sds((), jnp.int32), small_leaf=_sds((), jnp.int32))
    args = (_sds((_CN,), jnp.int32), _sds((3, _F, _BINS), jnp.float32),
            _sds((_CC, _F), jnp.int16), _sds((), jnp.int32),
            _sds((_CC,), jnp.bool_), _sds((_CN,), jnp.float32),
            _sds((_CN,), jnp.float32), _sds((_CN,), jnp.bool_),
            _sds((_F,), jnp.int32), sel)
    return Target(_split_chunk_step, args, dict(num_bins=_BINS))
