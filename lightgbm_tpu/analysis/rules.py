"""jaxlint built-in rules R1-R21.

Each rule is a generator over the :class:`~.core.PackageIndex`; see
``docs/ANALYSIS.md`` for the catalogue with examples and the pragma format.
Scope vocabulary used below:

* *hot function* — jit-decorated, reachable from a jit-decorated function
  through the package call graph, or nested inside one (its body is traced);
* *host driver* — a non-traced function whose ``for``/``while`` loop calls a
  jit-decorated function (the boosting/growth round loops).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from .core import (Finding, FuncInfo, PackageIndex, dotted_name,
                   has_cache_decorator, jit_info_from_call, register_rule)

_NUMPY_ALIASES = ("np", "numpy", "onp")
_SYNC_ATTRS = ("item", "tolist")
_NP_SYNC_FUNCS = ("asarray", "array")
_CAST_BUILTINS = ("float", "int", "bool")
_SHAPE_ATTRS = ("shape", "ndim", "size", "dtype")
_COLLECTIVES = ("psum", "pmax", "pmin", "pmean", "all_gather", "psum_scatter",
                "all_to_all", "ppermute", "pshuffle", "axis_index")
_PY_IMPURE_MODULES = ("time", "random")


def _own_body(fi: FuncInfo, include_nested: bool = False
              ) -> Iterator[ast.AST]:
    """Walk fi's body.  With include_nested=False (the default), nested
    function defs are skipped — each nested def is its own FuncInfo, so
    per-function iteration visits every node exactly ONCE (no duplicate
    findings) while lambdas, which have no FuncInfo, stay with the
    enclosing function.  include_nested=True additionally descends into
    nested defs; use it only when iterating top-level functions exclusively
    (R3 does, to see closure reads)."""

    def rec(node: ast.AST) -> Iterator[ast.AST]:
        for child in ast.iter_child_nodes(node):
            if (not include_nested and isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef))):
                continue
            yield child
            yield from rec(child)

    def top() -> Iterator[ast.AST]:
        # statement body only — decorators/defaults/annotations are the
        # ENCLOSING scope's code (a @partial(jax.jit, ...) decorator is not
        # a jit constructed "inside" the function it decorates)
        for stmt in fi.node.body:
            yield stmt
            if (not include_nested and isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef))):
                continue  # direct nested def: own FuncInfo covers its body
            yield from rec(stmt)

    return top()


def _is_np_attr(node: ast.AST, attrs) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr in attrs
            and isinstance(node.value, ast.Name)
            and node.value.id in _NUMPY_ALIASES)


def _mentions_param(node: ast.AST, params) -> bool:
    return any(isinstance(n, ast.Name) and n.id in params
               for n in ast.walk(node))


def _is_shape_like(node: ast.AST) -> bool:
    """Expressions like x.shape[0] / len(x) / x.ndim are Python ints at
    trace time — casting them is NOT a host sync."""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in _SHAPE_ATTRS:
            return True
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == "len"):
            return True
    return False


def _finding(fi: FuncInfo, node: ast.AST, rule: str, msg: str, hint: str
             ) -> Finding:
    return Finding(str(fi.module.path), getattr(node, "lineno", fi.node.lineno),
                   rule, msg, hint)


# ---------------------------------------------------------------------------
# R1 — host-sync-in-hot-path
# ---------------------------------------------------------------------------

@register_rule("R1", "host-sync-in-hot-path")
def r1_host_sync(pkg: PackageIndex) -> Iterator[Finding]:
    """``np.asarray``/``np.array``/``.item()``/``.tolist()`` force a device
    pull (or break the trace outright inside jit); builtin ``float``/``int``/
    ``bool`` applied to a traced parameter concretize it.  In a hot function
    any of these is a trace error or a silent sync; in a host driver loop it
    is a per-round round-trip (the ~45 ms/round tunnel syncs of
    docs/NEXT.md)."""
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            hot = pkg.is_hot(fi)
            driver = pkg.is_host_driver(fi)
            if not hot and not driver:
                continue
            where = "jit-traced code" if hot else "a jit-dispatching host loop"
            # in a host driver only the LOOP body is hot: a pull before/after
            # the loop is a once-per-call cost (e.g. a numpy-returning API
            # boundary), not the per-round sync class this rule hunts
            loop_nodes = PackageIndex._loop_body_walk(fi) if driver else None
            for node in _own_body(fi):
                if not isinstance(node, ast.Call):
                    continue
                if loop_nodes is not None and node not in loop_nodes:
                    continue
                if _is_np_attr(node.func, _NP_SYNC_FUNCS):
                    name = dotted_name(node.func)
                    yield _finding(
                        fi, node, "R1",
                        f"{name}(...) in {where} ({fi.qualname})",
                        "use jnp inside traces; hoist host pulls out of the "
                        "round loop or batch them into one sync")
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _SYNC_ATTRS and not node.args):
                    yield _finding(
                        fi, node, "R1",
                        f".{node.func.attr}() device pull in {where} "
                        f"({fi.qualname})",
                        "keep scalars on device (0-d arrays) until the host "
                        "actually needs them")
                elif (hot and isinstance(node.func, ast.Name)
                        and node.func.id in _CAST_BUILTINS
                        and len(node.args) == 1
                        and _mentions_param(node.args[0], fi.params)
                        and not _is_shape_like(node.args[0])):
                    yield _finding(
                        fi, node, "R1",
                        f"{node.func.id}() concretizes a traced argument in "
                        f"{fi.qualname}",
                        "operate on the traced value with jnp, or mark the "
                        "argument static if it is genuinely a Python scalar")


# ---------------------------------------------------------------------------
# R2 — recompile-hazard
# ---------------------------------------------------------------------------

def _enclosing_is_cached(fi: FuncInfo) -> bool:
    cur: Optional[FuncInfo] = fi
    while cur is not None:
        if has_cache_decorator(cur.node):
            return True
        cur = cur.parent
    return False


@register_rule("R2", "recompile-hazard")
def r2_recompile(pkg: PackageIndex) -> Iterator[Finding]:
    """Two statically-detectable recompile classes: (a) a ``jax.jit`` created
    inside a function body keys a FRESH trace cache per call — every
    invocation of the enclosing function retraces (and leaks compiled
    executables), unless the enclosing function is memoized; (b) a
    list/dict/set literal passed for a ``static_argnames``/``static_argnums``
    parameter is unhashable and raises at call time.  Per-round retraces
    from *varying* static values are a runtime property — the compile
    counter in ``utils/sanitizer.py`` is the matching runtime check."""
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            if _enclosing_is_cached(fi):
                continue
            # (a) nested jit: decorated nested defs...
            if fi.jit is not None and fi.parent is not None:
                if not _enclosing_is_cached(fi.parent):
                    yield _finding(
                        fi, fi.node, "R2",
                        f"jit-decorated {fi.qualname} is created per call of "
                        f"{fi.parent.qualname} (fresh trace cache each time)",
                        "hoist the jit to module level, or memoize the "
                        "factory (functools.lru_cache / an explicit cache)")
            # ...and jax.jit(...) call expressions in the body
            for node in _own_body(fi):
                if isinstance(node, ast.Call) and \
                        jit_info_from_call(node) is not None:
                    yield _finding(
                        fi, node, "R2",
                        f"jax.jit(...) constructed inside {fi.qualname} "
                        "(fresh trace cache per call)",
                        "hoist to module level or memoize the factory "
                        "(functools.lru_cache) keyed by the static config")

        # (b) unhashable static args at resolved jitted call sites
        for fi in mod.functions.values():
            for node in _own_body(fi):
                if not isinstance(node, ast.Call):
                    continue
                target = pkg.resolve_call(mod, node.func)
                callee = pkg.lookup(target) if target else None
                if callee is None or callee.jit is None:
                    continue
                static_idx = set(callee.jit.static_argnums)
                static_names = set(callee.jit.static_argnames)
                pos_params = callee.params
                for i, arg in enumerate(node.args):
                    name = pos_params[i] if i < len(pos_params) else None
                    if (i in static_idx or name in static_names) and \
                            isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                        yield _finding(
                            fi, arg, "R2",
                            f"unhashable literal for static arg "
                            f"{name or i} of {callee.qualname}",
                            "pass a tuple/frozenset — static args are "
                            "hashed into the jit cache key")
                for kw in node.keywords:
                    by_num = (kw.arg in pos_params
                              and pos_params.index(kw.arg) in static_idx)
                    if (kw.arg in static_names or by_num) and isinstance(
                            kw.value, (ast.List, ast.Dict, ast.Set)):
                        yield _finding(
                            fi, kw.value, "R2",
                            f"unhashable literal for static arg {kw.arg} of "
                            f"{callee.qualname}",
                            "pass a tuple/frozenset — static args are "
                            "hashed into the jit cache key")


# ---------------------------------------------------------------------------
# R3 — use-after-donate
# ---------------------------------------------------------------------------

def _donated_arg_names(callee: FuncInfo, call: ast.Call):
    """Names of simple variables the call site passes in donated positions."""
    jit = callee.jit
    donated_idx = set(jit.donate_argnums)
    donated_names = set(jit.donate_argnames)
    pos_params = callee.params
    for i, arg in enumerate(call.args):
        pname = pos_params[i] if i < len(pos_params) else None
        if i in donated_idx or pname in donated_names:
            dn = dotted_name(arg)
            if dn:
                yield dn
    for kw in call.keywords:
        if kw.arg in donated_names or (
                kw.arg in pos_params and pos_params.index(kw.arg) in donated_idx):
            dn = dotted_name(kw.value)
            if dn:
                yield dn


@register_rule("R3", "use-after-donate")
def r3_use_after_donate(pkg: PackageIndex) -> Iterator[Finding]:
    """A buffer passed through a ``donate_argnums`` position is DEAD after
    the call — XLA may have reused its memory for the output.  Reading the
    old variable afterwards raises at best (deleted-buffer error) and
    corrupts silently at worst (sharded aliasing edge cases).  The windowed
    grower donates its 1.5 GB-at-Epsilon hist state, so its host loop must
    thread the state linearly: always rebind (``state = f(state, ...)``),
    never touch the pre-call name again."""
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            if fi.parent is not None:
                # nested defs are covered by their top-level ancestor's
                # include_nested walk (closure reads of a donated name must
                # be visible); iterating them again would double-report
                continue
            calls = []  # (lineno, donated-name)
            rebinds = {}  # name -> sorted lines where it is (re)assigned
            loads = {}  # name -> lines where it is read
            for node in _own_body(fi, include_nested=True):
                if isinstance(node, ast.Call):
                    target = pkg.resolve_call(mod, node.func)
                    callee = pkg.lookup(target) if target else None
                    if callee is not None and callee.jit is not None and (
                            callee.jit.donate_argnums
                            or callee.jit.donate_argnames):
                        for dn in _donated_arg_names(callee, node):
                            calls.append((node.lineno, dn, callee.qualname))
                if isinstance(node, (ast.Name, ast.Attribute)):
                    dn = dotted_name(node)
                    if dn is None:
                        continue
                    ctx = getattr(node, "ctx", None)
                    if isinstance(ctx, ast.Store):
                        rebinds.setdefault(dn, []).append(node.lineno)
                    elif isinstance(ctx, ast.Load):
                        loads.setdefault(dn, []).append(node.lineno)
            for call_line, dn, callee_name in calls:
                # first rebind at/after the call line kills the old binding
                # (x = f(x) rebinds on the call line itself)
                rebind_line = min(
                    (ln for ln in rebinds.get(dn, []) if ln >= call_line),
                    default=None)
                for load_line in loads.get(dn, []):
                    if load_line <= call_line:
                        continue
                    if rebind_line is not None and load_line >= rebind_line:
                        continue
                    yield Finding(
                        str(mod.path), load_line, "R3",
                        f"{dn} read after being donated to {callee_name} "
                        f"(line {call_line}) in {fi.qualname}",
                        "rebind the donated variable to the call result "
                        "(state = f(state, ...)) and only use the new value")


# ---------------------------------------------------------------------------
# R4 — collective-axis-name
# ---------------------------------------------------------------------------

@register_rule("R4", "collective-axis-name")
def r4_axis_names(pkg: PackageIndex) -> Iterator[Finding]:
    """Every string-literal axis name fed to a collective must be one of the
    axis constants the mesh module declares (``DATA_AXIS``/``FEATURE_AXIS``
    in ``parallel/mesh.py``): a typo'd axis name fails only when that code
    path finally runs under ``shard_map``, usually on real hardware.  Names
    that flow in as function parameters are dynamic and skipped."""
    declared = pkg.axis_names
    if not declared:
        return
    for mod in pkg.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = dotted_name(node.func)
            if fn is None:
                continue
            parts = fn.split(".")
            if parts[-1] not in _COLLECTIVES:
                continue
            if not (len(parts) == 1 or parts[-2] == "lax"):
                continue
            axis_arg = None
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis"):
                    axis_arg = kw.value
            if axis_arg is None:
                want = 0 if parts[-1] == "axis_index" else 1
                if len(node.args) > want:
                    axis_arg = node.args[want]
            if axis_arg is None:
                continue
            if isinstance(axis_arg, ast.Constant) and isinstance(
                    axis_arg.value, str):
                if axis_arg.value not in declared:
                    yield Finding(
                        str(mod.path), axis_arg.lineno, "R4",
                        f"collective axis name {axis_arg.value!r} is not a "
                        f"declared mesh axis {sorted(declared)}",
                        "use the axis constants from parallel/mesh.py "
                        "(DATA_AXIS / FEATURE_AXIS), not ad-hoc strings")
            elif isinstance(axis_arg, ast.Name):
                # resolve the name to a module-level string constant (local
                # or imported); unresolvable names (parameters, locals) are
                # dynamic and out of static reach
                nm = axis_arg.id
                value = mod.str_constants.get(nm)
                if value is None:
                    imp = mod.imports.get(nm)
                    if imp is not None and imp[0] == "func":
                        src = pkg.modules.get(imp[1][0])
                        if src is not None:
                            value = src.str_constants.get(imp[1][1])
                if value is not None and value not in declared:
                    yield Finding(
                        str(mod.path), axis_arg.lineno, "R4",
                        f"collective axis name {nm}={value!r} is not a "
                        f"declared mesh axis {sorted(declared)}",
                        "use the axis constants from parallel/mesh.py "
                        "(DATA_AXIS / FEATURE_AXIS), not ad-hoc strings")


# ---------------------------------------------------------------------------
# R5 — impure-under-jit
# ---------------------------------------------------------------------------

@register_rule("R5", "impure-under-jit")
def r5_impure(pkg: PackageIndex) -> Iterator[Finding]:
    """Python-level side effects inside traced code run ONCE at trace time
    and never again: ``time.*`` / stdlib ``random`` / ``np.random`` calls
    bake a single host value into the compiled program, and ``global``/
    ``nonlocal`` writes mutate host state from inside a trace (executed at
    trace time, silently skipped on cached calls).  Use ``jax.random`` with
    threaded keys, pass times in as arguments, and carry state through
    function returns."""
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            if not pkg.is_hot(fi):
                continue
            for node in _own_body(fi):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    kind = ("global" if isinstance(node, ast.Global)
                            else "nonlocal")
                    yield _finding(
                        fi, node, "R5",
                        f"{kind} write ({', '.join(node.names)}) inside "
                        f"traced {fi.qualname} runs at trace time only",
                        "thread state through arguments/returns instead of "
                        "mutating host scope under jit")
                if not isinstance(node, ast.Call):
                    continue
                fn = dotted_name(node.func)
                if fn is None:
                    continue
                parts = fn.split(".")
                if parts[0] in _PY_IMPURE_MODULES and len(parts) > 1:
                    yield _finding(
                        fi, node, "R5",
                        f"{fn}() inside traced {fi.qualname} is evaluated "
                        "once at trace time",
                        "pass host values in as arguments; use jax.random "
                        "for in-trace randomness")
                elif (len(parts) >= 3 and parts[0] in _NUMPY_ALIASES
                        and parts[1] == "random"):
                    yield _finding(
                        fi, node, "R5",
                        f"{fn}() host RNG inside traced {fi.qualname} "
                        "(one sample baked into the trace)",
                        "use jax.random with an explicitly threaded key")


# ---------------------------------------------------------------------------
# R6 — fusable-round-loop
# ---------------------------------------------------------------------------

_HOST_CONSUMER_ATTRS = ("item", "tolist")


def _call_names(node: ast.AST) -> set:
    """Simple names mentioned anywhere in `node`."""
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _statement_branch_contexts(root: ast.AST) -> dict:
    """Map each statement under `root` to its chain of enclosing if-arms
    ((id(if_node), arm), ...) — statements in the body vs orelse of the
    same ``if`` are mutually exclusive within one iteration."""
    out: dict = {}

    def rec(stmts, ctx) -> None:
        for st in stmts:
            out[st] = ctx
            if isinstance(st, ast.If):
                rec(st.body, ctx + ((id(st), 0),))
                rec(st.orelse, ctx + ((id(st), 1),))
            elif isinstance(st, ast.Match):
                for arm, case in enumerate(st.cases):
                    rec(case.body, ctx + ((id(st), arm),))
            elif isinstance(st, (ast.For, ast.While)):
                rec(st.body, ctx)
                rec(st.orelse, ctx)
            elif isinstance(st, ast.With):
                rec(st.body, ctx)
            elif isinstance(st, ast.Try):
                rec(st.body, ctx)
                rec(st.orelse, ctx)
                rec(st.finalbody, ctx)
                for h in st.handlers:
                    rec(h.body, ctx)

    rec(getattr(root, "body", []), ())
    return out


def _mutually_exclusive(ctx_a, ctx_b) -> bool:
    """True when the two branch contexts share an ``if`` with different
    arms — at most one of the statements runs per iteration."""
    arms_a = dict(ctx_a)
    return any(if_id in arms_a and arms_a[if_id] != arm
               for if_id, arm in ctx_b)


@register_rule("R6", "fusable-round-loop")
def r6_fusable_round_loop(pkg: PackageIndex) -> Iterator[Finding]:
    """Two consecutive jitted dispatches on the same DONATED state inside
    a host round loop, with no host consumer of the first call's results
    between them, are one fused dispatch waiting to happen: each extra
    dispatch costs a tunnel round-trip (~1-1.5 ms) and splits the round
    into separately scheduled XLA programs (the windowed grower's round-6
    admit/pass split — fused in round 7, docs/PERF_NOTES.md).  A host
    read (``np.asarray``/``.item()``/``float()`` of the first call's
    output) between the two is a REAL data dependency the host consumes
    — the loop genuinely needs the sync (or an async-read protocol) and
    is not flagged."""
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            if not pkg.is_host_driver(fi):
                continue
            # pair dispatches PER LOOP: two single-dispatch loops in
            # sequence share nothing per-iteration and must not pair
            # (nested loops revisit their nodes under the outer loop too
            # — `seen` dedups the identical finding)
            loops = [node for node in _own_body(fi)
                     if isinstance(node, (ast.For, ast.While))]
            seen = set()
            for loop in loops:
                loop_nodes = set(ast.walk(loop)) - {loop}
                branch_ctx = _statement_branch_contexts(loop)
                donated_calls = []  # (line, assigned, donated, qualname, ctx)
                dispatch_nodes = set()  # AST nodes inside dispatch assigns
                for node in _own_body(fi):
                    if node not in loop_nodes:
                        continue
                    if isinstance(node, ast.Assign) and isinstance(
                            node.value, ast.Call):
                        call = node.value
                        target = pkg.resolve_call(mod, call.func)
                        callee = pkg.lookup(target) if target else None
                        if callee is not None and callee.jit is not None and (
                                callee.jit.donate_argnums
                                or callee.jit.donate_argnames):
                            assigned = set()
                            for t in node.targets:
                                assigned |= _call_names(t)
                            donated_calls.append((
                                node.lineno, assigned,
                                set(_donated_arg_names(callee, call)),
                                callee.qualname, branch_ctx.get(node, ())))
                            dispatch_nodes.update(ast.walk(node))
                consumers = []  # (lineno, mentioned-names) — sync calls
                loads = []  # (lineno, name) — bare reads OUTSIDE dispatches
                for node in _own_body(fi):
                    if node not in loop_nodes:
                        continue
                    if isinstance(node, ast.Call):
                        is_sync = _is_np_attr(node.func, _NP_SYNC_FUNCS) or (
                            isinstance(node.func, ast.Attribute)
                            and node.func.attr in _HOST_CONSUMER_ATTRS) or (
                            isinstance(node.func, ast.Name)
                            and node.func.id in _CAST_BUILTINS)
                        if is_sync:
                            consumers.append((node.lineno, _call_names(node)))
                    if (isinstance(node, ast.Name)
                            and isinstance(getattr(node, "ctx", None), ast.Load)
                            and node not in dispatch_nodes):
                        # reads INSIDE a dispatch are device arguments, not
                        # host consumption (run_pass(state, info) is still
                        # fusable); a sync call inside a dispatch argument
                        # (int(np.asarray(info)[0])) is caught above
                        loads.append((node.lineno, node.id))
                donated_calls.sort(key=lambda e: e[0])
                for (la, assigned, _d_a, name_a, ctx_a), (
                        lb, _as_b, donated_b, name_b, ctx_b) in zip(
                        donated_calls, donated_calls[1:]):
                    threaded = assigned & donated_b
                    if not threaded:
                        continue
                    if _mutually_exclusive(ctx_a, ctx_b):
                        # if/else arms: only one dispatch runs per
                        # iteration — nothing to fuse
                        continue
                    # a host consumer suppresses the finding — either an
                    # explicit sync call touching the first dispatch's
                    # outputs (lc <= lb: a consumer ON the second
                    # dispatch's line still counts), or a bare read of a
                    # non-threaded output outside any dispatch
                    # (`if info[0]: break` implies a real host data
                    # dependency even without a recognizable sync call)
                    side_outputs = assigned - donated_b
                    consumed = any(
                        la < lc <= lb and (names & assigned)
                        for lc, names in consumers) or any(
                        la < ll <= lb and nm in side_outputs
                        for ll, nm in loads)
                    if consumed:
                        continue
                    key = (la, lb, name_a, name_b)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield Finding(
                        str(mod.path), lb, "R6",
                        f"{name_b} re-dispatches donated state "
                        f"{sorted(threaded)} produced by {name_a} (line {la}) "
                        f"in {fi.qualname}'s round loop with no host consumer "
                        "between them",
                        "fuse both phases into one jitted round body (one "
                        "dispatch/round); if the host truly needs a value "
                        "between them, read it asynchronously one round behind "
                        "(utils/sanitizer.py async_pull_*)")


# ---------------------------------------------------------------------------
# R7 — host-nonfinite-guard
# ---------------------------------------------------------------------------

_NONFINITE_FUNCS = ("isnan", "isfinite", "isinf")
_NONFINITE_HOST_MODULES = _NUMPY_ALIASES + ("math",)
_DEVICE_NP_ALIASES = ("jnp", "jax")


@register_rule("R7", "host-nonfinite-guard")
def r7_host_nonfinite_guard(pkg: PackageIndex) -> Iterator[Finding]:
    """The NaN-guard anti-pattern: checking per-round tensors for
    non-finite values FROM THE HOST inside a grower/boosting loop.  A
    ``np.isnan(...)``/``math.isnan(...)`` on a device value forces a
    blocking device pull every round (the ~45 ms tunnel sync class R1
    hunts), and ``float()``/``bool()``/``int()`` wrapped around a
    device-side ``jnp.isnan(...)``/``jnp.isfinite(...)`` result is the
    same sync wearing a jnp costume.  The supported pattern costs
    nothing: fold the finite flag into the round's device info vector and
    read it asynchronously one round behind (the windowed grower's guard,
    utils/guards.py + utils/sanitizer.py async_pull_*), or accumulate a
    device-side first-bad-iteration scalar checked at existing sync
    points (models/gbdt.py _guard_accumulate/_guard_check)."""
    hint = ("keep the finite check ON DEVICE: fold it into the round's "
            "info vector and resolve it one round behind "
            "(utils/sanitizer.py async_pull_*), or accumulate a device "
            "flag checked at existing sync points — see "
            "docs/ROBUSTNESS.md and models/gbdt.py::_guard_accumulate")
    def _device_nonfinite_call(node: ast.AST) -> Optional[str]:
        """Dotted name of a jnp/jax is{nan,finite,inf} call inside node."""
        for inner in ast.walk(node):
            if not isinstance(inner, ast.Call):
                continue
            ifn = dotted_name(inner.func)
            if ifn is None:
                continue
            iparts = ifn.split(".")
            if (iparts[-1] in _NONFINITE_FUNCS
                    and iparts[0] in _DEVICE_NP_ALIASES):
                return ifn
        return None

    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            if not pkg.is_host_driver(fi):
                continue
            loop_nodes = PackageIndex._loop_body_walk(fi)
            flagged = set()  # nodes already reported via an if/while test
            for node in _own_body(fi):
                if node not in loop_nodes:
                    continue
                # if/while/assert on a jnp.is* result: __bool__ on a
                # device array — the implicit form of the same sync
                if isinstance(node, (ast.If, ast.While, ast.Assert)):
                    cond = node.test
                    ifn = _device_nonfinite_call(cond)
                    if ifn is not None:
                        flagged.update(ast.walk(cond))
                        yield _finding(
                            fi, cond, "R7",
                            f"branching on {ifn}(...) forces a blocking "
                            f"device pull (implicit bool) in "
                            f"{fi.qualname}'s round loop", hint)
                    continue
                if not isinstance(node, ast.Call) or node in flagged:
                    continue
                fn = dotted_name(node.func)
                if fn is not None:
                    parts = fn.split(".")
                    if (len(parts) >= 2 and parts[-1] in _NONFINITE_FUNCS
                            and parts[0] in _NONFINITE_HOST_MODULES):
                        yield _finding(
                            fi, node, "R7",
                            f"host-side {fn}() non-finite check on a "
                            f"per-round tensor in {fi.qualname}'s round loop "
                            "(one blocking device pull per round)", hint)
                        continue
                if (isinstance(node.func, ast.Name)
                        and node.func.id in _CAST_BUILTINS and node.args):
                    ifn = _device_nonfinite_call(node.args[0])
                    if ifn is not None:
                        yield _finding(
                            fi, node, "R7",
                            f"{node.func.id}({ifn}(...)) pulls a "
                            f"device-side finite flag synchronously in "
                            f"{fi.qualname}'s round loop", hint)


# ---------------------------------------------------------------------------
# R8 — unbucketed-predict-entry
# ---------------------------------------------------------------------------

_MASK_PRODUCING_FNS = ("nonzero", "flatnonzero", "where", "isnan",
                       "isfinite", "isinf")


def _masklike_names(fi: FuncInfo) -> set:
    """Names assigned (anywhere in ``fi``) from a boolean-mask-shaped
    expression — a comparison, a bitwise mask combination (&, |, ~), or a
    ``np.nonzero``/``np.where``/``np.isnan``-class call.  Subscripting a
    batch with one of these produces a DATA-dependent row count, the shape
    class that defeats jit caching."""
    def masky(expr: ast.AST) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Compare):
                return True
            if isinstance(node, ast.BinOp) and isinstance(
                    node.op, (ast.BitAnd, ast.BitOr)):
                return True
            if isinstance(node, ast.UnaryOp) and isinstance(
                    node.op, ast.Invert):
                return True
            if isinstance(node, ast.Call):
                fn = dotted_name(node.func)
                if fn is not None and fn.split(".")[-1] in _MASK_PRODUCING_FNS:
                    return True
        return False

    out = set()
    for node in _own_body(fi):
        if isinstance(node, ast.Assign) and masky(node.value):
            for t in node.targets:
                out |= _call_names(t)
        elif isinstance(node, ast.AugAssign) and (
                masky(node.value)
                or isinstance(node.op, (ast.BitAnd, ast.BitOr))):
            out |= _call_names(node.target)
    return out


@register_rule("R8", "unbucketed-predict-entry")
def r8_unbucketed_predict_entry(pkg: PackageIndex) -> Iterator[Finding]:
    """A jitted entry point dispatched in a host loop with a DATA-dependent
    leading dimension — the ``X[active]`` anti-pattern the round-9 serving
    rework removed from prediction early-stopping: every distinct mask
    count is a new shape, so the entry RETRACES AND RECOMPILES once per
    distinct active-set size (O(chunks) compiles for one predict call).
    The supported pattern keeps every row in a bucket-padded batch and
    masks inactive rows ON DEVICE (ops/predict.py ``active=`` +
    models/gbdt.py ``_predict_bucket``), so the loop reuses one compiled
    executable."""
    hint = ("pad the batch to a shape bucket and pass the mask to the "
            "device (ops/predict.py active=); shrinking the array "
            "host-side recompiles per distinct mask count — see "
            "docs/ANALYSIS.md (R8) and models/gbdt.py "
            "_predict_raw_early_stop")
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            if not pkg.is_host_driver(fi):
                continue
            loop_nodes = PackageIndex._loop_body_walk(fi)
            masky = _masklike_names(fi)
            for node in _own_body(fi):
                if node not in loop_nodes or not isinstance(node, ast.Call):
                    continue
                target = pkg.resolve_call(mod, node.func)
                callee = pkg.lookup(target) if target else None
                if callee is None or callee.jit is None:
                    continue
                args = list(node.args) + [kw.value for kw in node.keywords]
                for arg in args:
                    if not isinstance(arg, ast.Subscript):
                        continue
                    idx = arg.slice
                    if isinstance(idx, ast.Name) and idx.id in masky:
                        why = f"boolean-mask subscript [{idx.id}]"
                    elif isinstance(idx, ast.Compare):
                        why = "inline comparison-mask subscript"
                    else:
                        continue
                    yield _finding(
                        fi, node, "R8",
                        f"{callee.qualname} dispatched in {fi.qualname}'s "
                        f"loop with a data-dependent leading dimension "
                        f"({why}): one retrace + compile per distinct mask "
                        "count", hint)


# ---------------------------------------------------------------------------
# R9 — untimed-device-section
# ---------------------------------------------------------------------------

_TIMER_ATTRS = ("perf_counter", "monotonic", "perf_counter_ns",
                "monotonic_ns")
# calls that prove the device queue drained (or a host pull resolved)
# between a dispatch and the timer read: the wall-clock delta then covers
# the device work it claims to measure
_R9_SYNC_ATTRS = ("asarray", "array", "item", "tolist", "block_until_ready",
                  "sync_pull", "async_pull_result")


def _is_timer_call(node: ast.AST) -> bool:
    """``time.perf_counter()`` / ``time.time()`` / ``time.monotonic()``
    (any module alias whose name contains "time"; bare ``perf_counter``
    from a ``from time import`` also counts)."""
    if not isinstance(node, ast.Call):
        return False
    fn = dotted_name(node.func)
    if fn is None:
        return False
    parts = fn.split(".")
    if parts[-1] in _TIMER_ATTRS:
        return True
    return len(parts) >= 2 and parts[-1] == "time" and "time" in parts[0]


def _r9_sync_lines(fi: FuncInfo) -> list:
    out = []
    for node in _own_body(fi):
        if not isinstance(node, ast.Call):
            continue
        fn = dotted_name(node.func)
        if fn is not None and fn.split(".")[-1] in _R9_SYNC_ATTRS:
            out.append(node.lineno)
        elif (isinstance(node.func, ast.Name)
                and node.func.id in _CAST_BUILTINS and node.args):
            # int()/float()/bool() of a device value is itself a blocking
            # pull — as a SUPPRESSOR, over-matching is the safe direction
            out.append(node.lineno)
    return out


@register_rule("R9", "untimed-device-section")
def r9_untimed_device_section(pkg: PackageIndex) -> Iterator[Finding]:
    """The async-dispatch mistiming anti-pattern: a ``time.perf_counter()``
    / ``time.time()`` delta taken around a jitted dispatch with no
    accounted sync between the dispatch and the second timer read.  JAX
    dispatch is ASYNCHRONOUS — the jitted call returns as soon as the
    work is enqueued (~1-1.5 ms through the tunnel), so the delta measures
    enqueue time, not device compute, and every benchmark built on it is
    fiction (the round-4 ``block_until_ready``-returns-early episode in
    docs/PERF_NOTES.md is the companion failure on the sync side).  A host
    pull (``np.asarray``/``.item()``/``sync_pull``) or an
    ``async_pull_result`` between the dispatch and the read makes the
    delta honest and suppresses the finding — as does routing the section
    through ``utils/profiling.py::timed_section(sync=True)``, which drains
    the queue with the documented host-pull sync."""
    hint = ("resolve a host pull of the dispatched work before reading the "
            "timer (np.asarray of a tiny slice, utils/sanitizer.py "
            "sync_pull/async_pull_result), or use utils/profiling.py "
            "timed_section(sync=True) — raw perf_counter around an async "
            "dispatch times the enqueue, not the device")
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            if pkg.is_hot(fi):
                continue  # time.* under trace is R5's business
            timer_starts: dict = {}  # var -> [assignment lines]
            subs = []  # (line, names in the Sub expr, has inline timer call)
            dispatch_lines = []
            for node in _own_body(fi):
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and _is_timer_call(node.value)):
                    timer_starts.setdefault(
                        node.targets[0].id, []).append(node.lineno)
                    continue
                if isinstance(node, ast.BinOp) and isinstance(
                        node.op, ast.Sub):
                    has_timer_call = any(_is_timer_call(x)
                                         for x in ast.walk(node))
                    names = {x.id for x in ast.walk(node)
                             if isinstance(x, ast.Name)}
                    if names:
                        subs.append((node.lineno, names, has_timer_call))
                if isinstance(node, ast.Call):
                    target = pkg.resolve_call(mod, node.func)
                    callee = pkg.lookup(target) if target else None
                    if callee is not None and callee.jit is not None:
                        dispatch_lines.append(node.lineno)
            # a delta reads a timer var against a second timer value —
            # either an inline timer call (perf_counter() - t0) or another
            # timer var (t1 - t0, the stored-second-read spelling); decided
            # after the walk, when timer_starts is complete
            deltas = [(ln, names) for ln, names, inline in subs
                      if (names & set(timer_starts))
                      and (inline
                           or len(names & set(timer_starts)) >= 2)]
            if not dispatch_lines or not deltas:
                continue
            sync_lines = _r9_sync_lines(fi)
            for dline, names in deltas:
                for var in names & set(timer_starts):
                    starts = [ln for ln in timer_starts[var] if ln < dline]
                    if not starts:
                        continue
                    s = max(starts)  # the binding this delta reads
                    disp = [d for d in dispatch_lines if s < d < dline]
                    if not disp:
                        continue
                    last_d = max(disp)
                    # a blocking pull at-or-after the last dispatch drains
                    # the queue — earlier dispatches retired with it.
                    # `<=` on the left: np.asarray(step(x)) puts the pull
                    # on the dispatch's own line, and over-matching is the
                    # safe direction for a suppressor
                    if any(last_d <= sl <= dline for sl in sync_lines):
                        continue
                    yield Finding(
                        str(mod.path), dline, "R9",
                        f"wall-clock delta (started line {s}) read around "
                        f"a jitted dispatch (line {last_d}) with no "
                        f"accounted sync before the read in {fi.qualname}",
                        hint)


# ---------------------------------------------------------------------------
# R10 — sync-in-span-close
# ---------------------------------------------------------------------------

# calls that PULL a device value to the host (fresh blocking syncs when the
# value lives on device).  Narrower than R9's suppressor list on purpose:
# here matching is a POSITIVE finding, so the sanitizer-routed accounted
# reads (sync_pull / async_pull_result) are explicitly allowed — closing a
# span AT an accounted sync is the correct pattern, adding a fresh pull to
# "drain for the timer" is the bug.
_R10_FRESH_PULL_ATTRS = ("asarray", "array", "item", "tolist",
                         "block_until_ready", "device_get")
_R10_ACCOUNTED = ("sync_pull", "async_pull_result")
_R10_CLOSE_NAMES = ("__exit__", "close", "end", "finish")


def _is_contextmanager(node: ast.FunctionDef) -> bool:
    return any((dotted_name(d) or "").split(".")[-1] == "contextmanager"
               for d in node.decorator_list)


def _r10_close_paths(mod) -> Iterator:
    """(FuncInfo, first_line) pairs whose body (from first_line on, or all
    of it for None) is a span CLOSE path: the ``__exit__``/``close`` of a
    *Span-named* class, or the after-``yield`` tail of a
    ``@contextmanager`` generator named like a span."""
    for fi in mod.functions.values():
        parts = fi.qualname.split(".")
        if (len(parts) >= 2 and parts[-1] in _R10_CLOSE_NAMES
                and any("span" in p.lower() for p in parts[:-1])):
            yield fi, None
            continue
        if "span" in parts[-1].lower() and _is_contextmanager(fi.node):
            ylines = [n.lineno for n in ast.walk(fi.node)
                      if isinstance(n, (ast.Yield, ast.YieldFrom))]
            if ylines:
                yield fi, min(ylines)


@register_rule("R10", "sync-in-span-close")
def r10_sync_in_span_close(pkg: PackageIndex) -> Iterator[Finding]:
    """The tracing twin of R9's mistiming class: a span ``__exit__`` /
    ``close`` (or the after-yield tail of a ``@contextmanager`` span) that
    performs a FRESH device pull (``np.asarray``/``.item()``/
    ``block_until_ready``/a host cast) to make its duration "honest".
    Spans are opened around device work everywhere the round loops run, so
    a pull in the close path reintroduces exactly the per-round blocking
    sync the round-7 protocol removed — one hidden ~45 ms tunnel
    round-trip per span, and the DispatchCounter budget pins fail with
    tracing on.  The correct pattern is the inverse: close the span AT an
    existing accounted sync (the async info resolve, the predict entry's
    ``sync_pull``) via ``obs.trace.record_span`` — the accounted readers
    (``sync_pull``/``async_pull_result``) are therefore allowed here."""
    hint = ("span closes must not pull: record device-inclusive intervals "
            "retroactively at an existing accounted sync "
            "(obs/trace.py record_span after the async info resolve or the "
            "entry's sync_pull) and let context-manager spans stay "
            "host-causal — see docs/OBSERVABILITY.md 'Span tracing'")
    for mod in pkg.modules.values():
        for fi, after_line in _r10_close_paths(mod):
            for node in _own_body(fi):
                if not isinstance(node, ast.Call):
                    continue
                if after_line is not None and node.lineno <= after_line:
                    continue
                fn = dotted_name(node.func)
                last = fn.split(".")[-1] if fn else None
                if last in _R10_ACCOUNTED:
                    continue
                if last in _R10_FRESH_PULL_ATTRS:
                    yield _finding(
                        fi, node, "R10",
                        f"span close path {fi.qualname} performs a fresh "
                        f"device pull ({last}) — a hidden blocking sync "
                        "per span", hint)


# ---------------------------------------------------------------------------
# R11 — whole-array-vmem-staging
# ---------------------------------------------------------------------------

def _r11_imports_pallas(mod) -> bool:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom):
            src = node.module or ""
            if "pallas" in src or any("pallas" in (a.name or "")
                                      for a in node.names):
                return True
        elif isinstance(node, ast.Import):
            if any("pallas" in a.name for a in node.names):
                return True
    return False


def _r11_variable_dim(shape_node: ast.AST) -> bool:
    """A block shape with a NON-literal dimension — a runtime-dependent
    size (``n``, ``n_pad``, ``x.shape[0]``...), the signature of a block
    sized by the data rather than a fixed tile."""
    if not isinstance(shape_node, ast.Tuple):
        return False
    return any(not isinstance(e, ast.Constant) for e in shape_node.elts)


def _r11_const_index_map(node: ast.AST) -> bool:
    """True when an index_map lambda sends EVERY grid step to the same
    block (body is a literal, or a tuple of literals, ignoring the grid
    args) — with a constant map the block IS the whole array."""
    if not isinstance(node, ast.Lambda):
        return False
    body = node.body
    elts = body.elts if isinstance(body, ast.Tuple) else [body]
    return all(isinstance(e, ast.Constant) for e in elts)


def _r11_module_int_consts(mod) -> set:
    """Module-level ``NAME = <int literal>`` assignments — fixed tile
    constants (``_CHUNK = 512``) that are fine in scratch shapes."""
    out = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Constant) and isinstance(
                node.value.value, int):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


_R11_CONST_NAME = re.compile(r"^_?[A-Z][A-Z0-9_]*$")


def _r11_scratch_dim_ok(e: ast.AST, consts: set) -> bool:
    """A scratch dimension is fine when it is a literal, a module-level
    int constant, or an ALL-CAPS identifier (the config-tile convention —
    ``_CHUNK``, ``FB``, a budget-derived feature block); a lowercase
    name (``n``, ``n_pad``, ``rows``) is the data-sized signature."""
    if isinstance(e, ast.Constant):
        return True
    name = dotted_name(e)
    if name:
        last = name.split(".")[-1]
        return last in consts or bool(_R11_CONST_NAME.match(last))
    return False


@register_rule("R11", "whole-array-vmem-staging")
def r11_whole_array_vmem_staging(pkg: PackageIndex) -> Iterator[Finding]:
    """A Pallas ``BlockSpec`` whose block shape carries a variable (data-
    dependent) dimension AND whose index map sends every grid step to the
    same block stages the ENTIRE array through VMEM: staging traffic is
    O(N) however little the kernel touches, and the scoped-VMEM budget
    turns into a hard row cap (the v1 partition kernel's deleted
    ``_MAX_VMEM_ROWS = 650_000`` was exactly this).  The fix pattern is
    an HBM ref + chunked DMA: keep the operand un-staged
    (``memory_space=pltpu.ANY``) and stream fixed-size chunks through a
    small double-buffered VMEM scratch via ``pltpu.make_async_copy``
    (ops/partition_pallas.py v2).  Grid-blocked specs (index map uses a
    grid arg) and fixed-size tiles are the NORMAL Pallas idiom and are
    not flagged; an intentionally staged small variable-size block (an
    O(S) per-segment table) takes a pragma with its reason.

    Round 16 (the megakernel's discipline): ``pltpu.VMEM(...)`` SCRATCH
    allocations are held to the same standard — a scratch buffer sized
    by a data-dependent dimension is whole-array staging by another
    name.  Literal dims, module-level int constants (``_CHUNK``), and
    ALL-CAPS config-tile names (a budget-derived feature block like
    ``FB``) are the normal idiom; a lowercase data name (``n``,
    ``n_pad``) is flagged."""
    hint = ("stage per-chunk, not per-array: give the operand "
            "memory_space=pltpu.ANY (HBM ref) and DMA fixed-size chunks "
            "into a VMEM scratch with pltpu.make_async_copy, double-"
            "buffered (copy chunk k+1 in while computing chunk k) — see "
            "ops/partition_pallas.py and docs/ANALYSIS.md R11")
    for mod in pkg.modules.values():
        if not _r11_imports_pallas(mod):
            continue
        consts = _r11_module_int_consts(mod)
        for fi in mod.functions.values():
            for node in _own_body(fi):
                if not isinstance(node, ast.Call):
                    continue
                fn = dotted_name(node.func)
                if fn and fn.split(".")[-1] == "VMEM" and node.args:
                    shape = node.args[0]
                    if isinstance(shape, ast.Tuple) and any(
                            not _r11_scratch_dim_ok(e, consts)
                            for e in shape.elts):
                        yield _finding(
                            fi, node, "R11",
                            f"VMEM scratch in {fi.qualname} is sized by a "
                            "data-dependent dimension: scratch residency "
                            "scales with the data and the VMEM budget "
                            "becomes a row cap", hint)
                    continue
                if not fn or fn.split(".")[-1] != "BlockSpec":
                    continue
                block_shape = node.args[0] if node.args else None
                index_map = node.args[1] if len(node.args) > 1 else None
                is_hbm_ref = False
                for kw in node.keywords:
                    if kw.arg == "block_shape":
                        block_shape = kw.value
                    if kw.arg == "index_map":
                        index_map = kw.value
                    if kw.arg == "memory_space" and (
                            dotted_name(kw.value) or "").endswith("ANY"):
                        is_hbm_ref = True  # nothing is staged
                if block_shape is None or not _r11_variable_dim(block_shape):
                    continue
                if is_hbm_ref:
                    continue
                if index_map is not None and not _r11_const_index_map(
                        index_map):
                    continue
                yield _finding(
                    fi, node, "R11",
                    f"BlockSpec in {fi.qualname} stages a variable-size "
                    "array whole in VMEM (non-literal block dimension, "
                    "constant index map): staging is O(N) and the VMEM "
                    "budget becomes a row cap", hint)


# ---------------------------------------------------------------------------
# R12 — raw-model-write
# ---------------------------------------------------------------------------

# name fragments marking an expression as a model/snapshot artifact path —
# matched case-insensitively against identifiers, attribute names, and
# string literals inside the written-path expression
_R12_ARTIFACT_TOKENS = ("model", "snapshot", "manifest", "checkpoint",
                        "ckpt")


def _r12_mentions_artifact(node: ast.AST) -> bool:
    for n in ast.walk(node):
        name = None
        if isinstance(n, ast.Name):
            name = n.id
        elif isinstance(n, ast.Attribute):
            name = n.attr
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            name = n.value
        if name is not None:
            low = name.lower()
            if any(t in low for t in _R12_ARTIFACT_TOKENS):
                return True
    return False


@register_rule("R12", "raw-model-write")
def r12_raw_model_write(pkg: PackageIndex) -> Iterator[Finding]:
    """A durable write of a model/snapshot artifact OUTSIDE
    utils/checkpoint.py: ``open(path, "w"/"wb")``, ``np.save``/
    ``np.savez[_compressed]``, or a hand-rolled ``os.replace`` whose
    target expression names a model/snapshot/manifest path.  Every
    durable model write must go through the atomic sha256-trailed helper
    (``checkpoint.atomic_write_text`` / ``save_snapshot``): a raw
    ``open(..., "w")`` torn by a crash leaves a half-file a restart
    happily parses into a half-model — the silent-corruption class the
    round-8 checkpoint layer exists to exclude — and a raw ``os.replace``
    without the fsync'd temp protocol can still publish an incompletely
    flushed file.  Writes of non-artifact paths (logs, predictions,
    metrics, data caches with their own CRC trailers) are not flagged;
    an intentional raw artifact write (e.g. generated source code whose
    name merely contains 'model') takes a pragma with its reason."""
    hint = ("route durable model writes through utils/checkpoint.py: "
            "atomic_write_text(path, text) for plain models, "
            "save_snapshot(path, text, iteration) for trailer-stamped "
            "snapshots, write_fleet_checkpoint for fleet rounds — see "
            "docs/ROBUSTNESS.md and docs/ANALYSIS.md R12")
    for mod in pkg.modules.values():
        if str(mod.path).endswith("checkpoint.py"):
            continue  # the sanctioned writer itself
        for fi in mod.functions.values():
            for node in _own_body(fi):
                if not isinstance(node, ast.Call):
                    continue
                fn = dotted_name(node.func) or ""
                last = fn.split(".")[-1]
                how = None
                if last == "open" and "." not in fn and node.args:
                    mode = None
                    if (len(node.args) > 1
                            and isinstance(node.args[1], ast.Constant)):
                        mode = node.args[1].value
                    for kw in node.keywords:
                        if (kw.arg == "mode"
                                and isinstance(kw.value, ast.Constant)):
                            mode = kw.value.value
                    if (isinstance(mode, str) and "w" in mode
                            and _r12_mentions_artifact(node.args[0])):
                        how = f"open(..., {mode!r})"
                elif (_is_np_attr(node.func,
                                  ("save", "savez", "savez_compressed"))
                      and any(_r12_mentions_artifact(a)
                              for a in node.args)):
                    how = f"np.{last}"
                elif (fn == "os.replace" and len(node.args) > 1
                      and _r12_mentions_artifact(node.args[1])):
                    how = "os.replace"
                if how is not None:
                    yield _finding(
                        fi, node, "R12",
                        f"{fi.qualname} writes a model/snapshot artifact "
                        f"via raw {how} — outside the atomic "
                        "sha256-trailed checkpoint helper, a crash can "
                        "leave a torn file a restart will trust", hint)


# ---------------------------------------------------------------------------
# R13 — collective-outside-fused-round
# ---------------------------------------------------------------------------

_R13_COLLECTIVES = ("psum", "psum_scatter", "all_gather", "pmax", "pmin",
                    "pmean", "all_to_all", "ppermute")


def _r13_body_has_collective(fi: FuncInfo) -> bool:
    for node in _own_body(fi, include_nested=True):
        if isinstance(node, ast.Call):
            fn = dotted_name(node.func)
            if fn and fn.split(".")[-1] in _R13_COLLECTIVES:
                return True
    return False


@register_rule("R13", "collective-outside-fused-round")
def r13_collective_outside_fused_round(pkg: PackageIndex) -> Iterator[Finding]:
    """A cross-device collective issued from a HOST round loop that also
    dispatches donated (fused-round) state — either an eager
    ``jax.lax.psum``/``psum_scatter``/``all_gather`` call, or a second
    jitted dispatch whose body performs the collective.  Either form
    reintroduces the per-round collective round-trip LightGBM's Network
    layer pays (a ReduceScatter per split): one extra dispatch per round
    plus a device-queue barrier at exactly the cadence the fused round
    exists to remove.  On the sharded path the merge belongs INSIDE the
    donated round body — one dispatch, the collective in-trace
    (ops/treegrow_windowed.py::_round_fused under shard_map,
    docs/DISTRIBUTED.md "Sharded fused rounds").  Collectives inside the
    donated callee itself are the FIX, not a finding; loops with no
    donated dispatch (setup/eval phases) are out of scope."""
    hint = ("fold the collective into the donated round body (psum/"
            "psum_scatter inside the shard_mapped fused round — see "
            "parallel/data_parallel.py::grow_tree_windowed_data_parallel "
            "and docs/ANALYSIS.md R13); if the host truly needs the "
            "reduced value, return it in the round's async info vector")
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            if pkg.is_hot(fi):
                continue
            loops = [node for node in _own_body(fi)
                     if isinstance(node, (ast.For, ast.While))]
            for loop in loops:
                loop_nodes = set(ast.walk(loop)) - {loop}
                donated_lines = set()
                for node in _own_body(fi):
                    if node not in loop_nodes or not isinstance(
                            node, ast.Call):
                        continue
                    target = pkg.resolve_call(mod, node.func)
                    callee = pkg.lookup(target) if target else None
                    if callee is not None and callee.jit is not None and (
                            callee.jit.donate_argnums
                            or callee.jit.donate_argnames):
                        donated_lines.add(node.lineno)
                if not donated_lines:
                    continue  # not a fused-round loop
                for node in _own_body(fi):
                    if node not in loop_nodes or not isinstance(
                            node, ast.Call):
                        continue
                    if node.lineno in donated_lines:
                        continue  # the fused round itself
                    fn = dotted_name(node.func) or ""
                    last = fn.split(".")[-1]
                    if last in _R13_COLLECTIVES:
                        yield _finding(
                            fi, node, "R13",
                            f"host-issued collective {fn}() in "
                            f"{fi.qualname}'s fused round loop — a "
                            "per-round collective dispatch OUTSIDE the "
                            "donated round body", hint)
                        continue
                    target = pkg.resolve_call(mod, node.func)
                    callee = pkg.lookup(target) if target else None
                    if (callee is not None and callee.jit is not None
                            and not (callee.jit.donate_argnums
                                     or callee.jit.donate_argnames)
                            and _r13_body_has_collective(callee)):
                        yield _finding(
                            fi, node, "R13",
                            f"{callee.qualname} (jitted, collective-"
                            f"bearing) dispatched per round in "
                            f"{fi.qualname}'s fused round loop — the "
                            "merge pays a second dispatch instead of "
                            "riding the donated round", hint)


# ---------------------------------------------------------------------------
# R14 — metadata-via-device-pull
# ---------------------------------------------------------------------------

_R14_META_ATTRS = ("shape", "ndim", "size", "dtype")


def _r14_np_convert_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and _is_np_attr(
        node.func, _NP_SYNC_FUNCS)


@register_rule("R14", "metadata-via-device-pull")
def r14_metadata_via_device_pull(pkg: PackageIndex) -> Iterator[Finding]:
    """Reading METADATA through a whole-array host conversion:
    ``np.asarray(x).shape`` / ``np.asarray(x).dtype`` /
    ``len(np.asarray(x))`` / ``x.shape[0].item()``.  On a jitted output
    the ``np.asarray`` is a BLOCKING device pull of the entire buffer —
    paid to read a property (``.shape``/``.dtype``/``len``) the array
    object already exposes for free, device or host (the exact class the
    round-14 review caught in ``grow_tree_windowed_data_parallel``, which
    read ``num_bins_pf``'s length via ``np.asarray`` once per tree).
    Unlike R1 this fires EVERYWHERE, not just hot paths: a metadata read
    never needs the conversion, so the pull is pure waste wherever it
    sits — and on host inputs it is still a gratuitous O(N) copy."""
    hint = ("read .shape/.dtype/len() directly off the array (device "
            "arrays expose them without a transfer), or np.shape(x) for "
            "maybe-list inputs; convert once and bind the result if the "
            "DATA is genuinely needed too")
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            for node in _own_body(fi):
                if (isinstance(node, ast.Attribute)
                        and node.attr in _R14_META_ATTRS
                        and _r14_np_convert_call(node.value)):
                    yield _finding(
                        fi, node, "R14",
                        f"np.asarray(...).{node.attr} in {fi.qualname}: "
                        "a whole-array pull/copy to read metadata the "
                        "array already exposes", hint)
                elif (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "len" and len(node.args) == 1
                        and _r14_np_convert_call(node.args[0])):
                    yield _finding(
                        fi, node, "R14",
                        f"len(np.asarray(...)) in {fi.qualname}: a "
                        "whole-array pull/copy to read a length "
                        ".shape already exposes", hint)
                elif (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item" and not node.args
                        and isinstance(node.func.value, ast.Subscript)
                        and isinstance(node.func.value.value, ast.Attribute)
                        and node.func.value.value.attr == "shape"):
                    yield _finding(
                        fi, node, "R14",
                        f".shape[...].item() in {fi.qualname}: shape "
                        "entries are Python ints already — .item() here "
                        "signals a device round-trip habit", hint)


# ---------------------------------------------------------------------------
# R15 — staging-alloc-in-serve-loop
# ---------------------------------------------------------------------------

_R15_FRESH_ALLOCS = ("empty", "zeros", "ones", "full")
_R15_HOST_SOURCES = _R15_FRESH_ALLOCS + ("asarray", "array", "empty_like",
                                         "zeros_like", "ones_like",
                                         "full_like")
_R15_UPLOADS = ("asarray", "array", "device_put")
_R15_JNP_ALIASES = ("jnp", "jax")


def _r15_is_fresh_alloc(node: ast.AST) -> bool:
    """np.empty/zeros/ones/full — a fresh host buffer per call."""
    return isinstance(node, ast.Call) and _is_np_attr(node.func,
                                                      _R15_FRESH_ALLOCS)


def _r15_is_upload_of_fresh_host(node: ast.AST) -> bool:
    """jnp.asarray / jnp.array / jax.device_put whose operand is itself a
    fresh host-array construction (np.zeros(...)/np.asarray(...)/...): a
    per-call allocate-then-upload.  Uploads of a NAMED buffer are clean —
    reusing a pinned buffer is exactly the sanctioned pattern."""
    if not (isinstance(node, ast.Call) and node.args
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _R15_UPLOADS
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in _R15_JNP_ALIASES):
        return False
    arg = node.args[0]
    return (isinstance(arg, ast.Call)
            and _is_np_attr(arg.func, _R15_HOST_SOURCES))


def _r15_is_predict_entry(node: ast.AST) -> bool:
    """An accounted serving dispatch: a call whose final name is a
    predict entry (predict / predict_raw / predict_coalesced / the
    predict_ops kernels) or the accounted ``sync_pull`` itself."""
    if not isinstance(node, ast.Call):
        return False
    fn = dotted_name(node.func) or ""
    last = fn.split(".")[-1]
    return last.startswith("predict") or last == "sync_pull"


@register_rule("R15", "staging-alloc-in-serve-loop")
def r15_staging_alloc_in_serve_loop(pkg: PackageIndex) -> Iterator[Finding]:
    """A fresh host staging allocation INSIDE a loop that also drives an
    accounted predict entry: per-iteration ``np.empty``/``np.zeros`` (a
    new batch buffer every request) or ``jnp.asarray``/``jax.device_put``
    of a freshly constructed host array (allocate-then-upload per call).
    A serving loop runs forever at request cadence, so a per-iteration
    staging buffer is allocator pressure + a page-faulting copy on every
    batch — the exact cost the pinned double-buffered staging in
    lightgbm_tpu/serve/runtime.py exists to remove (one buffer pair per
    bucket rung, one ``readinto``-style copy per request, reused across
    batches; the round-12 out-of-core reused-buffer discipline applied to
    serving).  Uploading a NAMED (hoisted, reused) buffer inside the loop
    is clean — that upload is the design.  Loops with no predict entry
    (setup, training drivers) are out of scope: R1/R14 own those."""
    hint = ("hoist the staging buffer out of the loop and reuse it "
            "(lightgbm_tpu/serve/runtime.py::_next_staging is the "
            "pattern: one pinned pair per bucket rung, filled per "
            "request, uploaded by name); see docs/ANALYSIS.md R15")
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            if pkg.is_hot(fi):
                continue  # traced bodies: allocation is R1/R11's domain
            for loop in _own_body(fi):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                nodes = list(ast.walk(loop))
                if not any(_r15_is_predict_entry(n) for n in nodes):
                    continue
                # an alloc wrapped directly in a flagged upload reports
                # ONCE (as the allocate-then-upload form), not twice
                wrapped = {id(n.args[0]) for n in nodes
                           if _r15_is_upload_of_fresh_host(n)}
                for n in nodes:
                    if _r15_is_fresh_alloc(n) and id(n) not in wrapped:
                        yield _finding(
                            fi, n, "R15",
                            f"per-iteration host staging allocation "
                            f"np.{n.func.attr}(...) in {fi.qualname}'s "
                            "serving loop — a fresh batch buffer every "
                            "request instead of a pinned reused one",
                            hint)
                    elif _r15_is_upload_of_fresh_host(n):
                        yield _finding(
                            fi, n, "R15",
                            f"{dotted_name(n.func)}(np.{n.args[0].func.attr}"
                            f"(...)) in {fi.qualname}'s serving loop — "
                            "allocate-then-upload of a fresh host array "
                            "per iteration", hint)


# ---------------------------------------------------------------------------
# R16 — mutation-outside-version-bump
# ---------------------------------------------------------------------------

# the ensemble state whose mutation MUST route through the versioned
# pack invalidation: the tree list and the per-tree leaf tables
_R16_ENSEMBLE_ATTRS = ("models", "_models", "leaf_value")
_R16_LIST_MUTATORS = ("append", "extend", "insert", "pop", "remove",
                      "clear", "sort", "reverse")
_R16_BUMP = "_invalidate_pred_cache"
# only serve/continual code paths are in scope: they run BESIDE live
# serving readers, where an unbumped mutation hands an in-flight predict
# a pack that no longer matches the trees (docs/ANALYSIS.md static-limits
# note covers the rest of the tree)
_R16_SCOPED_DIRS = ("serve", "continual")


def _r16_ensemble_attr(node: ast.AST) -> Optional[str]:
    """The ensemble attribute an expression touches: ``x.models`` /
    ``x._models`` / ``tree.leaf_value`` (as an Attribute), or a Subscript
    over one (``x.models[i]``, ``tree.leaf_value[k]``)."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr in _R16_ENSEMBLE_ATTRS:
        return node.attr
    return None


def _r16_has_bump(fi: FuncInfo) -> bool:
    for node in _own_body(fi):
        if isinstance(node, ast.Call):
            fn = dotted_name(node.func) or ""
            if fn.split(".")[-1] == _R16_BUMP:
                return True
    return False


@register_rule("R16", "mutation-outside-version-bump")
def r16_mutation_outside_version_bump(pkg: PackageIndex) -> Iterator[Finding]:
    """An ensemble-mutating write in serve/continual code that does not
    route through ``_invalidate_pred_cache``: assigning to ``.models`` /
    ``._models`` / ``.leaf_value`` (whole, element, or slice) or calling
    a list mutator on them, in a function whose own body never bumps the
    pack version.  The round-18 ``_packed`` cache is keyed on
    ``_pack_version``; a mutation that skips the bump leaves the CURRENT
    version's device pack describing trees that no longer exist — a
    live serving reader then returns predictions from the pre-mutation
    ensemble indefinitely (stale, not just racy), and the round-19 lock
    making bump+lookup atomic cannot help a bump that never happens.
    Scoped to modules under ``serve/`` and ``continual/`` directories —
    the code that runs beside live serving readers; trainer-side
    mutations elsewhere are covered by the versioned key's belt-and-
    braces components and the runtime budget pins (static-limits note in
    docs/ANALYSIS.md)."""
    hint = ("mutate, then call gbdt._invalidate_pred_cache('<reason>') in "
            "the SAME function (continual/refit.py::refit_leaves is the "
            "pattern) — or mutate a private clone and publish it through "
            "ServingRuntime.swap_model")
    for mod in pkg.modules.values():
        parts = getattr(mod.path, "parts", ())
        if not any(d in parts for d in _R16_SCOPED_DIRS):
            continue
        for fi in mod.functions.values():
            if _r16_has_bump(fi):
                continue
            for node in _own_body(fi):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        attr = _r16_ensemble_attr(t)
                        if attr is not None:
                            yield _finding(
                                fi, node, "R16",
                                f"write to .{attr} in {fi.qualname} "
                                "without a _pack_version bump — the "
                                "serving pack cache now describes trees "
                                "that no longer exist", hint)
                elif (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _R16_LIST_MUTATORS):
                    attr = _r16_ensemble_attr(node.func.value)
                    if attr is not None:
                        yield _finding(
                            fi, node, "R16",
                            f".{attr}.{node.func.attr}(...) in "
                            f"{fi.qualname} without a _pack_version bump "
                            "— an in-place ensemble edit invisible to "
                            "the versioned pack cache", hint)


# ---------------------------------------------------------------------------
# R17 — full-histogram-over-dcn
# ---------------------------------------------------------------------------

_R17_COLLECTIVES = ("psum", "psum_scatter", "all_gather", "pmean",
                    "all_to_all", "ppermute", "pmax", "pmin")
# gather-style calls whose result is top-k-shaped by construction: an
# operand assigned from one of these is an elected subset, not the
# full-F plane
_R17_TOPK_GATHERS = ("take_along_axis", "top_k", "dynamic_slice",
                     "dynamic_slice_in_dim")


def _r17_axis_mentions_dcn(axis_arg: ast.AST) -> bool:
    """The collective's axis expression references the DCN axis: the
    'dcn' string literal, the DCN_AXIS constant, or any dcn-named
    variable — including tuple axes like (ICI_AXIS, DCN_AXIS)."""
    for sub in ast.walk(axis_arg):
        if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                and "dcn" in sub.value.lower()):
            return True
        if isinstance(sub, ast.Name) and "dcn" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) and "dcn" in sub.attr.lower():
            return True
    return False


def _r17_hist_name(expr: ast.AST) -> Optional[str]:
    """The operand's name when it reads as a histogram buffer."""
    if isinstance(expr, ast.Subscript):
        return _r17_hist_name(expr.value)
    if isinstance(expr, ast.Name):
        nm = expr.id
    elif isinstance(expr, ast.Attribute):
        nm = expr.attr
    else:
        return None
    return nm if "hist" in nm.lower() else None


def _r17_topk_shaped(fi: FuncInfo, name: str) -> bool:
    """True when ``name`` is assigned (anywhere in the function) from a
    top-k gather — take_along_axis / top_k / dynamic_slice family — so a
    hist-named operand is actually an elected feature subset."""
    for node in _own_body(fi, include_nested=True):
        if not isinstance(node, ast.Assign):
            continue
        targets = {t.id for t in node.targets if isinstance(t, ast.Name)}
        if name not in targets:
            continue
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Call):
                fn = dotted_name(sub.func) or ""
                if fn.split(".")[-1] in _R17_TOPK_GATHERS:
                    return True
    return False


@register_rule("R17", "full-histogram-over-dcn")
def r17_full_histogram_over_dcn(pkg: PackageIndex) -> Iterator[Finding]:
    """A collective whose axis set includes the DCN axis moving a FULL
    histogram operand.  The hierarchical two-level merge's contract
    (docs/DISTRIBUTED.md "Hierarchical merge") is that full (…, F, B)
    histogram planes merge only INSIDE a slice's ICI axis — crossing
    DCN is reserved for top-k-shaped payloads (elected feature columns,
    gathered by the vote's indices) and scalars, because DCN bandwidth
    is an order of magnitude below ICI and a full-F merge there erases
    the multi-slice speedup at exactly the scale it was bought for.
    Statically: any ``jax.lax`` collective whose axis expression
    references the dcn axis and whose operand NAMES a histogram
    (``*hist*``) is flagged, unless that operand is assigned from a
    top-k gather (``take_along_axis``/``top_k``/``dynamic_slice``) in
    the same function — the elected-subset shape
    ``parallel/hierarchy.py::dcn_topk_best`` ships.  Name-heuristic by
    necessity (the AST has no avals); the jaxpr-audit ``dcn_max_bytes``
    contract pin is the sound byte-level half (docs/ANALYSIS.md)."""
    hint = ("merge full histograms over the ici axis only; cross dcn "
            "with the elected top-k feature columns "
            "(parallel/hierarchy.py::dcn_topk_best) or scalars — see "
            "docs/DISTRIBUTED.md 'Hierarchical merge' and the "
            "jaxpr-audit dcn_max_bytes pin")
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            if fi.parent is not None:
                # nested defs are walked through their ENCLOSING function
                # (include_nested below) — visiting them again would both
                # duplicate findings and lose sight of a top-k gather
                # assigned in the enclosing scope (the R3 discipline)
                continue
            for node in _own_body(fi, include_nested=True):
                if not isinstance(node, ast.Call):
                    continue
                fn = dotted_name(node.func)
                if fn is None or fn.split(".")[-1] not in _R17_COLLECTIVES:
                    continue
                if not node.args:
                    continue
                axis_arg = None
                for kw in node.keywords:
                    if kw.arg in ("axis_name", "axis"):
                        axis_arg = kw.value
                if axis_arg is None and len(node.args) > 1:
                    axis_arg = node.args[1]
                if axis_arg is None or not _r17_axis_mentions_dcn(axis_arg):
                    continue
                hist_nm = _r17_hist_name(node.args[0])
                if hist_nm is None:
                    continue
                if _r17_topk_shaped(fi, hist_nm):
                    continue
                yield _finding(
                    fi, node, "R17",
                    f"{fn}({hist_nm}, …) in {fi.qualname} moves a full "
                    "histogram operand across the dcn axis — the "
                    "cross-slice merge must be top-k-shaped or scalar",
                    hint)


# ---------------------------------------------------------------------------
# R18 — host-loop-over-independent-boosters
# ---------------------------------------------------------------------------

# the per-model entry points a fleet batches: one dispatch per round for
# ALL models (ops/treegrow_fleet.py) instead of one per model per round
_R18_ENTRIES = ("train_one_iter", "refit_leaves")
# "train" is a common verb — only the package entry spellings count
# (bare `train` from `from lightgbm_tpu import train`, or qualified
# through the canonical module aliases); `self.train()` methods do not
_R18_TRAIN_QUALS = ("train", "lgb.train", "engine.train",
                    "lightgbm_tpu.train", "lightgbm_tpu.engine.train")


def _r18_is_entry(fn: str) -> bool:
    last = fn.split(".")[-1]
    if last in _R18_ENTRIES:
        return True
    return fn in _R18_TRAIN_QUALS


def _r18_walk_no_defs(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk minus nested function defs — their bodies are their own
    FuncInfo's territory (the _own_body discipline)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield child
        yield from _r18_walk_no_defs(child)


def _r18_loop_assigned(loop: ast.For) -> set:
    """Names assigned by statements in the loop body — the loop-carried
    candidates.  A call argument reading one of these means iteration i
    consumes iteration i-1's output (warm-started training, a running
    score feeding the next refit): sequentially dependent, not a fleet."""
    out = set()
    for node in ast.walk(loop):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        out.add(sub.id)
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name):
                out.add(node.target.id)
    return out


@register_rule("R18", "host-loop-over-independent-boosters")
def r18_host_loop_over_independent_boosters(
        pkg: PackageIndex) -> Iterator[Finding]:
    """A host ``for`` loop training or refitting boosters one model per
    iteration with no cross-iteration data dependence: each pass calls
    ``train`` / ``train_one_iter`` / ``refit_leaves`` on its own
    element of a model list/dict, so every round costs one dispatch PER
    MODEL — B dispatch fees, B recompilation keys, B host round-trips —
    for work that is one vmapped dispatch in total.  The booster fleet
    (``lgb.train_fleet``, ``ops/treegrow_fleet.py``) trains B
    independent boosters in ONE donated dispatch per round, and
    ``continual.fleet_refit_leaves`` is the batched refit twin; at
    B=64 the batched path is the difference between a fleet sweep and a
    lunch break (BENCH_fleet artifacts).  A call argument that READS a
    name assigned inside the loop body is a loop-carried dependence
    (warm-start chains like ``bst = train(..., init_model=bst)``, a
    running score feeding the next refit) — sequential by construction,
    not flagged.  Name-heuristic on the entry spellings: bare/qualified
    package ``train`` plus any ``train_one_iter``/``refit_leaves``
    (methods named ``.train`` on other objects are out of scope)."""
    hint = ("batch the models: lgb.train_fleet(datasets, params) trains "
            "B boosters in one dispatch per round "
            "(lightgbm_tpu/models/fleet.py); "
            "continual.fleet_refit_leaves batches the refit — or "
            "suppress with the dependence that makes the loop "
            "sequential")
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            seen = set()
            for node in _own_body(fi):
                if not isinstance(node, ast.For):
                    continue
                carried = _r18_loop_assigned(node)
                for sub in _r18_walk_no_defs(node):
                    if not isinstance(sub, ast.Call) or id(sub) in seen:
                        continue
                    fn = dotted_name(sub.func)
                    if fn is None and isinstance(sub.func, ast.Attribute):
                        # subscripted receiver (lanes[i].train_one_iter):
                        # no dotted spelling, but the method name decides
                        if sub.func.attr in _R18_ENTRIES:
                            fn = sub.func.attr
                    if fn is None or not _r18_is_entry(fn):
                        continue
                    seen.add(id(sub))
                    arg_names = {
                        s.id for a in (list(sub.args)
                                       + [k.value for k in sub.keywords])
                        for s in ast.walk(a) if isinstance(s, ast.Name)}
                    if arg_names & carried:
                        continue  # loop-carried input: sequential
                    yield _finding(
                        fi, sub, "R18",
                        f"{fn}(...) inside {fi.qualname}'s host loop "
                        "trains/refits one model per iteration — B "
                        "independent models cost B dispatches per round "
                        "where a fleet costs one", hint)


# ---------------------------------------------------------------------------
# R19 — unbounded-retry
# ---------------------------------------------------------------------------

# IO/dispatch-ish call spellings worth retry discipline: a failure here is
# transient-by-nature (network, device runtime, filesystem), which is what
# tempts the swallow-and-spin loop this rule exists to catch
_R19_IO_RE = re.compile(
    r"(request|urlopen|fetch|download|upload|connect|send|recv|rpc|query"
    r"|dispatch|predict|submit|read|write|open|post|push|pull)",
    re.IGNORECASE)
# loop identifiers that evidence a retry BUDGET or DEADLINE — any of these
# appearing anywhere in the loop (test or body) means the author bounded it
_R19_BUDGET_RE = re.compile(
    r"(attempt|retr(y|ies)|budget|deadline|tries|remaining|give_up|giveup)",
    re.IGNORECASE)
# pacing call spellings: a loop that sleeps, backs off, or waits between
# attempts cannot hot-spin
_R19_PACING = ("sleep", "wait")
_R19_PACING_RE = re.compile(r"(backoff|jitter)", re.IGNORECASE)
# exception spellings broad enough to swallow EVERY transient failure —
# catching these without re-raising, bounding or pacing is the hallmark
_R19_BROAD = ("Exception", "BaseException", "OSError", "IOError",
              "EnvironmentError", "TimeoutError", "ConnectionError")


def _r19_is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in types:
        nm = dotted_name(e)
        if nm is not None and nm.split(".")[-1] in _R19_BROAD:
            return True
    return False


def _r19_handler_escapes(handler: ast.ExceptHandler) -> bool:
    """True when the handler leaves the loop or re-raises — the failure
    is surfaced, not swallowed back into another attempt."""
    for node in _r18_walk_no_defs(handler):
        if isinstance(node, (ast.Raise, ast.Break, ast.Return)):
            return True
    return False


def _r19_is_pacing_call(node: ast.Call) -> bool:
    fn = dotted_name(node.func)
    last = (fn.split(".")[-1] if fn is not None
            else getattr(node.func, "attr", ""))
    if last in _R19_PACING or _R19_PACING_RE.search(last or ""):
        return True
    # a bare `.get()` / `.get(timeout=...)` on some receiver is a BLOCKING
    # queue handoff — the worker-loop shape (the serve dispatcher): the
    # loop stalls for fresh WORK between iterations, so it cannot spin.
    # `dict.get(key)` passes positional args and does not count.
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr == "get" and not node.args)


def _r19_loop_is_paced_or_bounded(loop: ast.While) -> bool:
    for node in _r18_walk_no_defs(loop):
        if isinstance(node, ast.Call) and _r19_is_pacing_call(node):
            return True
        if isinstance(node, ast.Name) and _R19_BUDGET_RE.search(node.id):
            return True
        if (isinstance(node, ast.Attribute)
                and _R19_BUDGET_RE.search(node.attr)):
            return True
    # the loop TEST is not inside walk(loop)'s body-only iteration? it is —
    # ast.iter_child_nodes(While) yields test first; kept explicit anyway
    for node in ast.walk(loop.test):
        if isinstance(node, ast.Name) and _R19_BUDGET_RE.search(node.id):
            return True
    return False


@register_rule("R19", "unbounded-retry")
def r19_unbounded_retry(pkg: PackageIndex) -> Iterator[Finding]:
    """A ``while`` loop that swallows broad exceptions around an
    IO/dispatch-ish call and loops straight back into the next attempt —
    no sleep/backoff/jitter between tries, no attempt budget, no
    deadline.  Under a persistent failure (a device runtime wedged, an
    endpoint down, a full disk) the loop hot-spins: 100% host CPU,
    a log volcano, and — when the callee holds locks or device queues —
    a livelock that looks exactly like the hang it was written to
    survive.  The serve fleet's discipline is the counter-example
    (serve/fleet.py): every redispatch pays a retry-budget token, every
    restart backs off exponentially with jitter, and deadlines turn a
    sick fleet into typed shedding.  Statically: a ``while`` containing
    a ``try`` whose handler catches ``Exception``/``BaseException``/
    ``OSError``/``TimeoutError``/bare without raising or leaving the
    loop, whose try body makes an IO-ish call, in a loop with no pacing
    call (``sleep``/``wait``/``backoff``/``jitter``/blocking queue
    ``.get()``) and no budget/deadline identifier
    (``attempt``/``retry``/``budget``/``deadline``/``tries``/
    ``remaining``).  Narrow catches (``except Empty``) pass clean —
    they name the one expected failure instead of swallowing all of
    them."""
    hint = ("bound the loop: pace attempts (time.sleep with exponential "
            "backoff + jitter), spend a retry budget, or check a "
            "deadline — and re-raise or surface the error once the "
            "budget is gone (serve/fleet.py::_retry_or_fail_locked is "
            "the in-tree shape); narrow the except to the one expected "
            "failure where possible")
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            for node in _own_body(fi):
                if not isinstance(node, ast.While):
                    continue
                if _r19_loop_is_paced_or_bounded(node):
                    continue
                for sub in _r18_walk_no_defs(node):
                    if not isinstance(sub, ast.Try):
                        continue
                    broad = [h for h in sub.handlers
                             if _r19_is_broad_handler(h)
                             and not _r19_handler_escapes(h)]
                    if not broad:
                        continue
                    io_call = None
                    for b in sub.body:
                        for c in _r18_walk_no_defs(b):
                            if isinstance(c, ast.Call):
                                fn = (dotted_name(c.func)
                                      or getattr(c.func, "attr", ""))
                                if fn and _R19_IO_RE.search(fn):
                                    io_call = fn.split(".")[-1]
                                    break
                        if io_call:
                            break
                    if io_call is None:
                        continue
                    yield _finding(
                        fi, sub, "R19",
                        f"retry loop in {fi.qualname} swallows broad "
                        f"exceptions around {io_call}(...) with no "
                        "backoff, budget or deadline — a persistent "
                        "failure hot-spins forever", hint)
                    break  # one finding per loop is enough


# ---------------------------------------------------------------------------
# R20 — feature-axis-hist-collective
# ---------------------------------------------------------------------------


def _r20_axis_mentions_feature(axis_arg: ast.AST) -> bool:
    """The axis expression references the feature mesh axis: the string
    literal, the FEATURE_AXIS mesh constant, or a *feature*-named
    variable/attribute (feature_axis_name) — including inside a tuple."""
    for sub in ast.walk(axis_arg):
        if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                and "feature" in sub.value.lower()):
            return True
        if isinstance(sub, ast.Name) and "feature" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) and "feature" in sub.attr.lower():
            return True
    return False


@register_rule("R20", "feature-axis-hist-collective")
def r20_feature_axis_hist_collective(pkg: PackageIndex) -> Iterator[Finding]:
    """A collective whose axis set includes the FEATURE mesh axis moving a
    histogram operand.  The 2-D (feature x row) layout's entire point
    (docs/DISTRIBUTED.md "2-D sharding", parallel/feature2d.py) is that
    each device's ``(F/d_f, N/d_r)`` bin tile builds histograms that are
    already COMPLETE for the owned feature block — the merge is the row
    psum alone, and the feature axis carries only the winner's go/no-go
    row broadcast and election scalars.  A histogram collective over the
    feature axis re-replicates what the layout made local, paying d_f
    times the merge bytes to erase the axis the mesh was widened for.
    Statically: any ``jax.lax`` collective whose axis expression
    references the feature axis and whose first operand NAMES a
    histogram (``*hist*``) is flagged, unless that operand is assigned
    from a top-k gather in the same function (an elected subset, the R17
    escape).  Name-heuristic by necessity; the ``windowed_round_2d_*``
    jaxpr-audit contracts are the sound IR-level half — they pin ZERO
    feature-axis collectives in the histogram phase and bill every axis's
    bytes (docs/ANALYSIS.md)."""
    hint = ("histograms over the feature-sharded bin tile are complete "
            "for the owned block by layout — merge over the row axis "
            "only, and cross the feature axis with the winner's row "
            "decisions or election scalars "
            "(parallel/feature2d.py, docs/DISTRIBUTED.md '2-D sharding')")
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            if fi.parent is not None:
                # nested defs walk through their enclosing function (the
                # R17 discipline): one visit, enclosing-scope gathers seen
                continue
            for node in _own_body(fi, include_nested=True):
                if not isinstance(node, ast.Call):
                    continue
                fn = dotted_name(node.func)
                if fn is None or fn.split(".")[-1] not in _R17_COLLECTIVES:
                    continue
                if not node.args:
                    continue
                axis_arg = None
                for kw in node.keywords:
                    if kw.arg in ("axis_name", "axis"):
                        axis_arg = kw.value
                if axis_arg is None and len(node.args) > 1:
                    axis_arg = node.args[1]
                if axis_arg is None or not _r20_axis_mentions_feature(
                        axis_arg):
                    continue
                hist_nm = _r17_hist_name(node.args[0])
                if hist_nm is None:
                    continue
                if _r17_topk_shaped(fi, hist_nm):
                    continue
                yield _finding(
                    fi, node, "R20",
                    f"{fn}({hist_nm}, …) in {fi.qualname} moves a "
                    "histogram operand across the feature axis — the "
                    "feature-sharded tile's histograms are complete for "
                    "the owned block; merge over the row axis only",
                    hint)


# ---------------------------------------------------------------------------
# R21 — unlinked-cross-thread-span
# ---------------------------------------------------------------------------

# span-creation call names (last dotted component): the obs/trace.py API
# surface that records into the span ring
_R21_SPAN_CALLS = ("span", "record_span", "Span")
# a span call carrying any of these keywords names its causal identity
# explicitly and is immune to the thread-local-stack trap
_R21_LINK_KWARGS = ("ctx", "parent", "links")


def _r21_thread_entry_names(mod) -> set:
    """Names of functions this module hands to a worker thread: the
    ``target=`` of any ``*.Thread(...)`` ctor, or the first argument of
    any ``*.submit(...)`` call (executor dispatch).  Both ``self._fn``
    and bare ``fn`` references resolve to their last component — entry
    functions are matched per-module by unqualified name."""
    names: set = set()

    def ref_name(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Attribute):
            return node.attr
        if isinstance(node, ast.Name):
            return node.id
        return None

    for fi in mod.functions.values():
        for node in _own_body(fi):
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "Thread"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        nm = ref_name(kw.value)
                        if nm:
                            names.add(nm)
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "submit" and node.args):
                nm = ref_name(node.args[0])
                if nm:
                    names.add(nm)
    return names


@register_rule("R21", "unlinked-cross-thread-span")
def r21_unlinked_cross_thread_span(pkg: PackageIndex) -> Iterator[Finding]:
    """(round 24) a span created INSIDE a thread-entry function — one
    handed to ``threading.Thread(target=...)`` or ``executor.submit(...)``
    in the same module — without an explicit causal identity: no ``ctx=``,
    ``parent=`` or ``links=`` keyword on the ``span(``/``record_span(``/
    ``Span(`` call, and no ``.link(`` call in the function's own body.
    The span stack that supplies implicit parentage is THREAD-LOCAL
    (``obs/trace.py``): on a worker thread it is empty, so an implicit
    span silently roots a brand-new top-level trace instead of joining
    the request that crossed the thread boundary — the request's slice
    then reconstructs without its dispatch/leg spans and the flight
    recorder shows a broken story (the round-24 cross-thread bugfix).
    Scoped to ``serve/``/``continual/`` modules — where worker threads
    carry request/rollover contexts; own-body only (a helper the entry
    calls is that helper's finding when it, too, becomes an entry —
    static-limits note in docs/ANALYSIS.md)."""
    hint = ("carry the TraceContext across the boundary explicitly: mint "
            "or receive a ctx on the queued work item and pass ctx=/"
            "parent= to span()/record_span(), or adopt members via "
            "links=[...] (serve/runtime.py::_dispatch_loop is the "
            "pattern); an intentional rootless maintenance span takes a "
            "pragma with its reason")
    for mod in pkg.modules.values():
        parts = getattr(mod.path, "parts", ())
        if not any(d in parts for d in _R16_SCOPED_DIRS):
            continue
        entries = _r21_thread_entry_names(mod)
        if not entries:
            continue
        for fi in mod.functions.values():
            if fi.qualname.split(".")[-1] not in entries:
                continue
            linked_via_api = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "link"
                for n in _own_body(fi))
            if linked_via_api:
                continue
            for node in _own_body(fi):
                if not isinstance(node, ast.Call):
                    continue
                fn = dotted_name(node.func)
                if fn is None or fn.split(".")[-1] not in _R21_SPAN_CALLS:
                    continue
                if any(kw.arg in _R21_LINK_KWARGS for kw in node.keywords):
                    continue
                yield _finding(
                    fi, node, "R21",
                    f"{fn}(...) in thread-entry {fi.qualname} without "
                    "ctx=/parent=/links= — the thread-local span stack is "
                    "empty on a worker thread, so this span roots a NEW "
                    "trace instead of joining the request that crossed "
                    "the boundary",
                    hint)
