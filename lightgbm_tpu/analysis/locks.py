"""jaxlint concurrency layer: lock-discipline rules L1-L5.

Rounds 18-20 made the package genuinely concurrent — the serve
coalescer/dispatcher pair, the continual runner, the periodic-snapshot
and watchdog threads, and the HTTP server all share mutable state behind
~10 ad-hoc locks.  PR 14 needed four review rounds of hand-auditing to
find its races; this layer turns that checklist into a pinned contract,
the way R1-R17 pinned jit purity and J1-J7 pinned the traced IR.

The pass builds a whole-package **lock model** from the ASTs the shared
:class:`~.core.PackageIndex` already parsed:

* *lock definitions* — ``self._x = threading.Lock()/RLock()/Condition()``
  (or the :mod:`lightgbm_tpu.utils.locktrace` factories ``lock()`` /
  ``rlock()`` / ``condition()``) on instance attributes, and the same
  assigned to module-level names.  Each definition gets a canonical id
  ``module.Class._attr`` / ``module._name``.
* *lock getters* — a zero-arg method whose body returns one of the
  class's known lock attributes (``GBDT._plock``): ``with self._plock():``
  acquires the attribute the getter manages.
* *acquisition sites* — ``with <lock>:`` blocks over any of the above.
* *held sets* — for every statement, which locks are held lexically; a
  method called ONLY from under-lock sites additionally inherits the
  intersection of its callers' held sets (one-level-deep contextual
  propagation through ``self.meth()`` and same-module calls), so the
  "caller holds _lock" helper idiom is analyzed in its real context.
* *guarded mutations* — attribute stores/augmented-assigns/del and
  mutating method calls (``append``/``pop``/``update``/...) recorded
  with the held set in effect.

Rules (catalogue + examples: docs/ANALYSIS.md "Concurrency layer"):

====  ==========================  ========================================
L1    lock-order-inversion        the static acquired-while-holding graph
                                  has a cycle (A taken under B somewhere,
                                  B under A elsewhere)
L2    blocking-call-under-lock    device sync (np.asarray / .item() /
                                  block_until_ready / sync_pull), file
                                  I/O, subprocess, socket or sleep inside
                                  a held-lock body
L3    unguarded-shared-mutation   an attribute mutated under a lock at
                                  one site is mutated with NO guard at
                                  another (outside __init__)
L4    wait-without-predicate-loop Condition.wait outside a while loop
                                  (lost-wakeup / spurious-wakeup hazard)
L5    orphan-thread               threading.Thread started with neither a
                                  join() nor a stop-Event path in module
====  ==========================  ========================================

Pragmas work exactly like the AST layer's::

    self._fh.write(line)  # jaxlint: disable=L2 (dedicated IO leaf lock)

Static limits (also in docs/ANALYSIS.md): ``.acquire()``/``.release()``
call pairs are invisible (only ``with`` blocks count); contextual held
sets propagate through resolvable calls only (``self.meth()`` and
same-module function calls — calls through containers or callbacks are
not followed); L2 flags DIRECT blocking calls under a lock, not blocking
work buried in transitively-called functions; L3 treats "held ANY lock
that guards this attribute elsewhere" as guarded.  The runtime witness
graph (:mod:`lightgbm_tpu.utils.locktrace`) covers the dynamic orders
the static pass cannot see.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import (Finding, FuncInfo, ModuleInfo, PackageIndex, dotted_name,
                   register_rule)

# receivers whose .write/.flush/.close under a lock count as file I/O:
# the attribute name (last segment) must contain one of these fragments
_FH_NAME_FRAGMENTS = ("fh", "file", "fp", "sock", "stream")
# attribute calls that are blocking no matter the receiver
_BLOCKING_ATTR_CALLS = {
    "block_until_ready": "device sync",
    "item": "device sync (host pull)",
    "tolist": "device sync (host pull)",
}
# numpy conversions of (potentially) device values
_NP_SYNC_FUNCS = ("asarray", "array")
_NUMPY_ALIASES = ("np", "numpy", "onp")
# dotted-call prefixes that block
_BLOCKING_DOTTED_PREFIXES = {
    "subprocess.": "subprocess",
    "socket.": "socket",
    "urllib.": "network I/O",
    "requests.": "network I/O",
    "shutil.": "file I/O",
    "time.sleep": "sleep",
    "os.replace": "file I/O",
    "os.rename": "file I/O",
    "os.fsync": "file I/O",
    "os.remove": "file I/O",
    "os.makedirs": "file I/O",
}
# container-mutating method names for L3 (same set R16 polices, plus dict)
_MUTATOR_METHODS = ("append", "extend", "insert", "pop", "popleft", "remove",
                    "clear", "update", "setdefault", "appendleft", "sort")
_LOCK_FACTORY_ATTRS = ("Lock", "RLock", "Condition")
_LOCKTRACE_FACTORIES = ("lock", "rlock", "condition")
_LOCKTRACE_MODULE_ALIASES = ("locktrace", "_locktrace", "_lt")


def _is_lock_ctor(node: ast.AST) -> Optional[str]:
    """``threading.Lock()`` / ``locktrace.condition("name")`` -> kind
    ("lock" | "rlock" | "condition"), else None."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)):
        if f.value.id == "threading" and f.attr in _LOCK_FACTORY_ATTRS:
            return {"Lock": "lock", "RLock": "rlock",
                    "Condition": "condition"}[f.attr]
        if (f.value.id in _LOCKTRACE_MODULE_ALIASES
                and f.attr in _LOCKTRACE_FACTORIES):
            return f.attr
    return None


class LockDef:
    """One declared lock: canonical id + kind + declaration site."""

    __slots__ = ("lock_id", "kind", "module", "line", "attr", "cls")

    def __init__(self, lock_id: str, kind: str, module: str, line: int,
                 attr: str, cls: Optional[str]) -> None:
        self.lock_id = lock_id      # "mod.Class._attr" or "mod._name"
        self.kind = kind            # lock | rlock | condition
        self.module = module
        self.line = line
        self.attr = attr            # bare attribute / name ("_cv")
        self.cls = cls              # owning class qualname or None


class MutationSite:
    __slots__ = ("fi", "node", "attr", "held")

    def __init__(self, fi: FuncInfo, node: ast.AST, attr: str,
                 held: Tuple[str, ...]) -> None:
        self.fi = fi
        self.node = node
        self.attr = attr  # "Class.attr" or "mod.name" for globals
        self.held = held


class LockModel:
    """The whole-package lock facts every L rule shares (built once per
    :func:`build_model` call and cached on the PackageIndex)."""

    def __init__(self, pkg: PackageIndex) -> None:
        self.pkg = pkg
        # lock_id -> LockDef
        self.locks: Dict[str, LockDef] = {}
        # (module, class) -> {attr -> lock_id}
        self.class_locks: Dict[Tuple[str, str], Dict[str, str]] = {}
        # module -> {name -> lock_id} (module-level locks)
        self.module_locks: Dict[str, Dict[str, str]] = {}
        # (module, class) -> {getter method name -> lock attr}
        self.lock_getters: Dict[Tuple[str, str], Dict[str, str]] = {}
        # fi.key -> locks held at entry via caller propagation
        self.entry_held: Dict[Tuple[str, str], Set[str]] = {}
        # directed acquired-while-holding edges:
        # (held, acquired) -> (file, line) of the first site seen
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self._collect_locks()
        self._collect_getters()
        self._propagate_entry_held()
        self._collect_edges()

    # -- lock discovery ---------------------------------------------------
    def _collect_locks(self) -> None:
        for mod in self.pkg.modules.values():
            # module-level: `_lock = threading.RLock()`
            for node in mod.tree.body:
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    kind = _is_lock_ctor(node.value)
                    if kind:
                        name = node.targets[0].id
                        lid = f"{mod.name}.{name}"
                        self.locks[lid] = LockDef(lid, kind, mod.name,
                                                  node.lineno, name, None)
                        self.module_locks.setdefault(mod.name, {})[name] = lid
            # instance attrs: `self._x = threading.Lock()` anywhere in a
            # method (init, lazy recreation, setstate)
            for fi in mod.functions.values():
                cls = self._owning_class(fi)
                if cls is None:
                    continue
                for node in self.pkg._own_body_walk(fi):
                    if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                        continue
                    value = node.value
                    kind = _is_lock_ctor(value)
                    if not kind:
                        continue
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        # `lock = self._pack_lock = threading.RLock()`
                        # chains: take every self-attr target
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            lid = f"{mod.name}.{cls}.{t.attr}"
                            if lid not in self.locks:
                                self.locks[lid] = LockDef(
                                    lid, kind, mod.name, node.lineno,
                                    t.attr, cls)
                            self.class_locks.setdefault(
                                (mod.name, cls), {})[t.attr] = lid

    @staticmethod
    def _owning_class(fi: FuncInfo) -> Optional[str]:
        """'Class' for a method qualname 'Class.meth', else None (nested
        defs inside methods keep the class prefix, so split on the last
        dot only when the prefix names a class — heuristically: the
        qualname has >= 2 parts and the function is not nested in
        another function)."""
        if fi.parent is not None:
            return LockModel._owning_class(fi.parent)
        if "." in fi.qualname:
            return fi.qualname.rsplit(".", 1)[0]
        return None

    def _collect_getters(self) -> None:
        """Methods whose body returns (or lazily creates and returns) one
        of the class's lock attributes: ``with self._plock():`` then
        acquires that attribute's lock."""
        for mod in self.pkg.modules.values():
            for fi in mod.functions.values():
                cls = self._owning_class(fi)
                if cls is None:
                    continue
                attrs = self.class_locks.get((mod.name, cls), {})
                if not attrs:
                    continue
                meth = fi.qualname.rsplit(".", 1)[-1]
                for node in ast.walk(fi.node):
                    if not isinstance(node, ast.Return) or node.value is None:
                        continue
                    v = node.value
                    # `return self._x` / `return lock` where lock was read
                    # from self._x earlier — handle the direct form plus a
                    # Name whose function body reads getattr(self,"_x")
                    target_attr = None
                    if (isinstance(v, ast.Attribute)
                            and isinstance(v.value, ast.Name)
                            and v.value.id == "self" and v.attr in attrs):
                        target_attr = v.attr
                    elif isinstance(v, ast.Name):
                        for sub in ast.walk(fi.node):
                            if (isinstance(sub, ast.Call)
                                    and isinstance(sub.func, ast.Name)
                                    and sub.func.id == "getattr"
                                    and len(sub.args) >= 2
                                    and isinstance(sub.args[1], ast.Constant)
                                    and sub.args[1].value in attrs):
                                target_attr = sub.args[1].value
                                break
                    if target_attr:
                        self.lock_getters.setdefault(
                            (mod.name, cls), {})[meth] = target_attr
                        break

    # -- resolution -------------------------------------------------------
    def resolve_lock_expr(self, fi: FuncInfo, expr: ast.AST) -> Optional[str]:
        """``with <expr>:`` -> lock_id when expr names a known lock."""
        mod = fi.module
        cls = self._owning_class(fi)
        # self._x  /  self._cv
        if (isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and cls is not None):
            return self.class_locks.get((mod.name, cls), {}).get(expr.attr)
        # module-level `_lock`
        if isinstance(expr, ast.Name):
            return self.module_locks.get(mod.name, {}).get(expr.id)
        # self._plock()  (lock getter)
        if (isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute)
                and isinstance(expr.func.value, ast.Name)
                and expr.func.value.id == "self" and cls is not None):
            attr = self.lock_getters.get((mod.name, cls), {}).get(
                expr.func.attr)
            if attr:
                return self.class_locks.get((mod.name, cls), {}).get(attr)
        return None

    def resolve_method_call(self, fi: FuncInfo, call: ast.Call
                            ) -> Optional[FuncInfo]:
        """Resolve ``self.meth(...)`` to the same-class FuncInfo, or a
        bare/module call through the core call graph."""
        f = call.func
        cls = self._owning_class(fi)
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self" and cls is not None):
            return fi.module.functions.get(f"{cls}.{f.attr}")
        target = self.pkg.resolve_call(fi.module, f)
        if target is not None:
            return self.pkg.lookup(target)
        return None

    # -- held-set walk ----------------------------------------------------
    def walk_held(self, fi: FuncInfo):
        """Yield ``(node, held)`` for every node in fi's own body, where
        ``held`` is the tuple of lock_ids held lexically at that node
        (entry-inherited locks first, innermost ``with`` last).  A
        ``with``-statement node and its context expressions are reported
        under the OUTER held set; its body under the inner one.  Nested
        defs/lambdas are skipped (they run later, on their own)."""
        base = tuple(sorted(self.entry_held.get(fi.key, set())))
        skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

        def emit(node: ast.AST, held: Tuple[str, ...]):
            if isinstance(node, skip):
                return
            yield (node, held)
            if isinstance(node, ast.With):
                acquired: List[str] = []
                for item in node.items:
                    lid = self.resolve_lock_expr(fi, item.context_expr)
                    if lid:
                        acquired.append(lid)
                    yield from walk(item, held)
                inner = held + tuple(a for a in acquired if a not in held)
                for stmt in node.body:
                    yield from emit(stmt, inner)
            else:
                yield from walk(node, held)

        def walk(node: ast.AST, held: Tuple[str, ...]):
            for child in ast.iter_child_nodes(node):
                yield from emit(child, held)

        for stmt in fi.node.body:
            yield from emit(stmt, base)

    def _direct_acquires(self, fi: FuncInfo) -> Set[str]:
        out: Set[str] = set()
        for node in self.pkg._own_body_walk(fi):
            if isinstance(node, ast.With):
                for item in node.items:
                    lid = self.resolve_lock_expr(fi, item.context_expr)
                    if lid:
                        out.add(lid)
        return out

    def _propagate_entry_held(self) -> None:
        """Contextual held sets: a PRIVATE function called only from
        under-lock sites inherits the intersection of its callers' held
        sets — the ``def _helper(self): ... # caller holds _lock`` idiom
        analyzed in its real context.  Public functions are API surface
        (open world: external callers the index cannot see), so they
        never inherit — only leading-underscore callees, whose in-package
        call graph is complete, do.  A bounded monotone fixpoint over
        resolvable calls (``self.meth()`` + same-module names)."""
        all_funcs = [fi for mod in self.pkg.modules.values()
                     for fi in mod.functions.values()]
        self.entry_held = {fi.key: set() for fi in all_funcs}
        for _ in range(4):  # bounded fixpoint (call chains here are shallow)
            sites: Dict[Tuple[str, str], List[Set[str]]] = {}
            for fi in all_funcs:
                for node, held in self.walk_held(fi):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = self.resolve_method_call(fi, node)
                    if (callee is not None and
                            callee.qualname.rsplit(".", 1)[-1].startswith("_")):
                        sites.setdefault(callee.key, []).append(set(held))
            changed = False
            for key, heldsets in sites.items():
                inter = set.intersection(*heldsets)
                if self.entry_held.get(key) != inter:
                    self.entry_held[key] = inter
                    changed = True
            if not changed:
                break

    # -- order graph ------------------------------------------------------
    def _collect_edges(self) -> None:
        """acquired-while-holding edges: lexical nesting plus one level of
        resolvable calls (f holds A, calls g, g's body acquires B)."""
        for mod in self.pkg.modules.values():
            for fi in mod.functions.values():
                for node, held in self.walk_held(fi):
                    acquired: List[str] = []
                    if isinstance(node, ast.With):
                        for item in node.items:
                            lid = self.resolve_lock_expr(fi, item.context_expr)
                            if lid:
                                acquired.append(lid)
                    elif isinstance(node, ast.Call):
                        callee = self.resolve_method_call(fi, node)
                        if callee is not None:
                            acquired.extend(self._direct_acquires(callee))
                    for lid in acquired:
                        for h in held:
                            if h == lid:
                                continue  # reentrant same-lock nesting
                            self.edges.setdefault(
                                (h, lid),
                                (str(mod.path), getattr(node, "lineno",
                                                        fi.node.lineno)))


_MODEL_CACHE: Dict[int, LockModel] = {}


def build_model(pkg: PackageIndex) -> LockModel:
    """The shared lock model, built once per PackageIndex instance."""
    model = _MODEL_CACHE.get(id(pkg))
    if model is None or model.pkg is not pkg:
        model = LockModel(pkg)
        _MODEL_CACHE.clear()  # one live index at a time; no unbounded growth
        _MODEL_CACHE[id(pkg)] = model
    return model


def _finding(fi: FuncInfo, node: ast.AST, rule: str, msg: str, hint: str
             ) -> Finding:
    return Finding(str(fi.module.path),
                   getattr(node, "lineno", fi.node.lineno), rule, msg, hint)


def _short(lock_id: str) -> str:
    """mod.Class._attr -> Class._attr (message brevity)."""
    parts = lock_id.split(".")
    return ".".join(parts[-2:]) if len(parts) >= 2 else lock_id


# ---------------------------------------------------------------------------
# L1 — lock-order-inversion
# ---------------------------------------------------------------------------

@register_rule("L1", "lock-order-inversion", layer="locks")
def l1_lock_order_inversion(pkg: PackageIndex) -> Iterator[Finding]:
    """Cycle in the static acquired-while-holding graph: lock B is taken
    while holding A at one site and A while holding B at another — two
    threads interleaving those sites deadlock.  Edges come from lexical
    ``with`` nesting plus one level of resolvable calls.  Fix: pick one
    global order (document it next to the lock definitions) and re-nest
    the minority site; the runtime witness graph (utils/locktrace)
    enforces the same order dynamically."""
    model = build_model(pkg)
    adj: Dict[str, Set[str]] = {}
    for (a, b) in model.edges:
        adj.setdefault(a, set()).add(b)

    def reachable(src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            cur = stack.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(adj.get(cur, ()))
        return False

    reported: Set[frozenset] = set()
    for (a, b), (path, line) in sorted(model.edges.items()):
        if not reachable(b, a):
            continue
        key = frozenset((a, b))
        if key in reported:
            continue
        reported.add(key)
        back = model.edges.get((b, a))
        via = (f"; reverse edge first seen at {back[0]}:{back[1]}"
               if back else " (via intermediate locks)")
        yield Finding(
            path, line, "L1",
            f"lock-order inversion: {_short(b)} acquired while holding "
            f"{_short(a)}, but the witness graph also orders "
            f"{_short(b)} before {_short(a)}{via}",
            "pick one global acquisition order and re-nest the minority "
            "site")


# ---------------------------------------------------------------------------
# L2 — blocking-call-under-lock
# ---------------------------------------------------------------------------

def _blocking_reason(fi: FuncInfo, node: ast.Call) -> Optional[str]:
    f = node.func
    # open(...)
    if isinstance(f, ast.Name) and f.id == "open":
        return "file I/O (open)"
    dotted = dotted_name(f)
    if dotted:
        for prefix, why in _BLOCKING_DOTTED_PREFIXES.items():
            if dotted == prefix.rstrip(".") or dotted.startswith(prefix):
                return why
        # np.asarray / np.array of a runtime value (shape-free heuristic:
        # any argument — the AST layer's R1 refines what is a device
        # value; under a lock ANY host materialization is suspect)
        parts = dotted.split(".")
        if (len(parts) == 2 and parts[0] in _NUMPY_ALIASES
                and parts[1] in _NP_SYNC_FUNCS):
            return "potential device sync (host materialization)"
    if isinstance(f, ast.Attribute):
        if f.attr in _BLOCKING_ATTR_CALLS:
            return _BLOCKING_ATTR_CALLS[f.attr]
        if f.attr == "sync_pull":
            return "accounted device sync (sync_pull)"
        if f.attr in ("write", "flush", "close", "tell"):
            recv = f.value
            recv_name = (recv.attr if isinstance(recv, ast.Attribute)
                         else recv.id if isinstance(recv, ast.Name) else "")
            if any(fragment in recv_name.lower()
                   for fragment in _FH_NAME_FRAGMENTS):
                return f"file I/O (.{f.attr} on {recv_name})"
        if f.attr == "join":
            # thread joins block indefinitely; string ".join" is filtered
            # by the receiver check (str literals/Names named *sep* etc.
            # rarely match the thread fragment)
            recv = f.value
            recv_name = (recv.attr if isinstance(recv, ast.Attribute)
                         else recv.id if isinstance(recv, ast.Name) else "")
            if "thread" in recv_name.lower() or recv_name in ("t", "worker"):
                return "thread join"
    # bare sync_pull (from-imported)
    if isinstance(f, ast.Name) and f.id == "sync_pull":
        return "accounted device sync (sync_pull)"
    return None


@register_rule("L2", "blocking-call-under-lock", layer="locks")
def l2_blocking_call_under_lock(pkg: PackageIndex) -> Iterator[Finding]:
    """A device sync (np.asarray / .item() / block_until_ready /
    sync_pull), file I/O, subprocess, socket, sleep or thread join runs
    with a lock held — every other thread contending on that lock stalls
    behind host-blocking work (the generalized PR 14 capi-refit finding:
    device pulls under ``_pack_lock`` stalled serving).  Fix: move the
    blocking work outside the critical section (snapshot under the lock,
    write after), or split the state lock from a dedicated IO leaf lock
    and pragma the leaf."""
    model = build_model(pkg)
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            for node, held in model.walk_held(fi):
                if not held or not isinstance(node, ast.Call):
                    continue
                why = _blocking_reason(fi, node)
                if why is None:
                    continue
                yield _finding(
                    fi, node, "L2",
                    f"{why} while holding {', '.join(_short(h) for h in held)}",
                    "hoist the blocking call out of the critical section "
                    "or split a dedicated IO leaf lock")


# ---------------------------------------------------------------------------
# L3 — unguarded-shared-mutation
# ---------------------------------------------------------------------------

def _mutations(model: LockModel, fi: FuncInfo
               ) -> Iterator[Tuple[ast.AST, str, Tuple[str, ...]]]:
    """(node, 'Class.attr' | 'mod:name', held) for every mutation of a
    self-attribute or module global in fi's own body."""
    cls = model._owning_class(fi)
    mod = fi.module

    def attr_of(t: ast.AST) -> Optional[str]:
        # self.x  => Class.x ; self.x[k] => Class.x ; global NAME[k]
        if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id == "self" and cls is not None):
            return f"{cls}.{t.attr}"
        if isinstance(t, ast.Subscript):
            return attr_of(t.value)
        if isinstance(t, ast.Name) and t.id in _module_globals(mod):
            return f"{mod.name}:{t.id}"
        return None

    for node, held in model.walk_held(fi):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                a = attr_of(t)
                if a:
                    yield (node, a, held)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                a = attr_of(t)
                if a:
                    yield (node, a, held)
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr in _MUTATOR_METHODS):
            a = attr_of(node.func.value)
            if a:
                yield (node, a, held)


_GLOBALS_CACHE: Dict[str, Set[str]] = {}


def _module_globals(mod: ModuleInfo) -> Set[str]:
    """Names declared ``global`` inside any function of the module — the
    only module-level names whose in-function rebinding L3 considers
    (import-time assignments are single-threaded by definition)."""
    cached = _GLOBALS_CACHE.get(mod.name)
    if cached is not None:
        return cached
    out: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Global):
            out.update(node.names)
    _GLOBALS_CACHE[mod.name] = out
    return out


@register_rule("L3", "unguarded-shared-mutation", layer="locks")
def l3_unguarded_shared_mutation(pkg: PackageIndex) -> Iterator[Finding]:
    """Guard inference, the lock-discipline analogue of R16: when the
    mutation sites of an attribute (or declared-global) are MOSTLY under
    a lock, a site holding none of the guards races them.  Inference is
    majority-vote (RacerD-style): an attribute counts as lock-guarded
    only when at least half of its mutation sites hold a lock — a single
    incidental under-lock store among many bare trainer-path stores does
    not make the attribute "guarded".
    ``__init__``/``__new__``/``__setstate__`` bodies are construction-
    time (pre-publication) and exempt.  A site under a DIFFERENT lock
    than its siblings passes this rule (multi-lock designs exist); the
    runtime witness layer sees what the static union cannot."""
    model = build_model(pkg)
    _GLOBALS_CACHE.clear()
    sites: Dict[Tuple[str, str], List[MutationSite]] = {}
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            leaf = fi.qualname.rsplit(".", 1)[-1]
            ctor = leaf in ("__init__", "__new__", "__setstate__")
            for node, attr, held in _mutations(model, fi):
                if ctor:
                    continue
                sites.setdefault((mod.name, attr), []).append(
                    MutationSite(fi, node, attr, held))
    for (modname, attr), muts in sorted(sites.items()):
        guards: Set[str] = set()
        for m in muts:
            guards.update(m.held)
        if not guards:
            continue
        bare = [m for m in muts if not (set(m.held) & guards)]
        if not bare or len(bare) > len(muts) - len(bare):
            continue  # majority unguarded: the lock section is incidental
        for m in bare:
            guarded_eg = next(x for x in muts if x.held)
            yield _finding(
                m.fi, m.node, "L3",
                f"{attr.split('.')[-1]} mutated with no lock held, but "
                f"guarded by {_short(sorted(guards)[0])} at "
                f"{guarded_eg.fi.module.path.name}:"
                f"{getattr(guarded_eg.node, 'lineno', 0)}",
                "take the same lock here, or pragma with the reason the "
                "site cannot race (e.g. single-thread phase)")


# ---------------------------------------------------------------------------
# L4 — wait-without-predicate-loop
# ---------------------------------------------------------------------------

@register_rule("L4", "wait-without-predicate-loop", layer="locks")
def l4_wait_without_predicate_loop(pkg: PackageIndex) -> Iterator[Finding]:
    """``Condition.wait`` outside a ``while``: spurious wakeups and
    notify-before-wait races make a bare ``if``-guarded (or unguarded)
    wait return with the predicate still false.  Only receivers that
    resolve to a known Condition are checked (``queue.Queue`` internals
    etc. are out of scope); ``wait_for`` embeds its own loop and passes."""
    model = build_model(pkg)
    for mod in pkg.modules.values():
        for fi in mod.functions.values():
            cls = model._owning_class(fi)
            # condition attrs visible to this function
            cond_attrs = {
                attr for attr, lid in model.class_locks.get(
                    (mod.name, cls), {}).items()
                if model.locks[lid].kind == "condition"} if cls else set()
            cond_names = {
                name for name, lid in model.module_locks.get(
                    mod.name, {}).items()
                if model.locks[lid].kind == "condition"}
            if not cond_attrs and not cond_names:
                continue
            # statement -> enclosing-while map over fi's own body
            in_while: Set[ast.AST] = set()

            def mark(node: ast.AST, inside: bool) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef, ast.Lambda)):
                        continue
                    now = inside or isinstance(child, ast.While)
                    if inside:
                        in_while.add(child)
                    mark(child, now)

            for stmt in fi.node.body:
                mark(stmt, isinstance(stmt, ast.While))
            for node in pkg._own_body_walk(fi):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "wait"):
                    continue
                recv = node.func.value
                is_cond = (
                    (isinstance(recv, ast.Attribute)
                     and isinstance(recv.value, ast.Name)
                     and recv.value.id == "self"
                     and recv.attr in cond_attrs)
                    or (isinstance(recv, ast.Name) and recv.id in cond_names))
                if not is_cond or node in in_while:
                    continue
                yield _finding(
                    fi, node, "L4",
                    "Condition.wait outside a while loop — a spurious "
                    "wakeup or a notify landing before the wait returns "
                    "with the predicate still false",
                    "use `while not pred: cv.wait(...)` or cv.wait_for")


# ---------------------------------------------------------------------------
# L5 — orphan-thread
# ---------------------------------------------------------------------------

def _thread_ctor_sites(fi: FuncInfo
                       ) -> Iterator[Tuple[ast.AST, Optional[str]]]:
    """(ctor call node, bound name) for `x = threading.Thread(...)` /
    `self._t = threading.Thread(...)` in fi's own body."""
    for node in _own_body_nodes(fi):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if not (isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute)
                and v.func.attr == "Thread"
                and isinstance(v.func.value, ast.Name)
                and v.func.value.id == "threading"):
            continue
        name = None
        t = node.targets[0]
        if isinstance(t, ast.Name):
            name = t.id
        elif (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
              and t.value.id == "self"):
            name = t.attr
        yield (v, name)


def _own_body_nodes(fi: FuncInfo) -> Iterator[ast.AST]:
    def rec(node: ast.AST) -> Iterator[ast.AST]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            yield child
            yield from rec(child)

    for stmt in fi.node.body:
        yield stmt
        yield from rec(stmt)


def _aliased_join(mod: ModuleInfo, name: str) -> bool:
    """The swap-join idiom: some function in the module binds a local
    from ``self.<name>`` (e.g. ``t, self._thread = self._thread, None``)
    and also calls ``.join(`` — the thread handle is joined through the
    alias, not the attribute."""
    for fi in mod.functions.values():
        reads_attr = False
        joins = False
        for node in _own_body_nodes(fi):
            if isinstance(node, ast.Assign):
                for sub in ast.walk(node.value):
                    if (isinstance(sub, ast.Attribute) and sub.attr == name
                            and isinstance(sub.value, ast.Name)
                            and sub.value.id == "self"):
                        reads_attr = True
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr == "join"):
                joins = True
        if reads_attr and joins:
            return True
    return False


@register_rule("L5", "orphan-thread", layer="locks")
def l5_orphan_thread(pkg: PackageIndex) -> Iterator[Finding]:
    """``threading.Thread`` constructed with no stop path visible in the
    module: the bound name (``self._thread`` / local ``t``) is never
    ``.join()``-ed anywhere in the module AND the constructing function
    wires no stop ``threading.Event`` (the ``Event`` + daemon +
    ``stop.set()`` generator idiom).  Orphan threads outlive tests,
    pin the interpreter at exit (non-daemon) or die mid-write (daemon),
    and are invisible to shutdown paths."""
    for mod in pkg.modules.values():
        src = "\n".join(mod.source_lines)
        for fi in mod.functions.values():
            for ctor, name in _thread_ctor_sites(fi):
                if name is not None and (f"{name}.join(" in src
                                         or f"{name}[0].join(" in src):
                    continue
                if name is not None and _aliased_join(mod, name):
                    continue
                # stop-Event pattern: the constructing function also
                # creates a threading.Event whose .set() appears in module
                has_event = False
                for node in _own_body_nodes(fi):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr == "Event"
                            and isinstance(node.func.value, ast.Name)
                            and node.func.value.id == "threading"):
                        has_event = True
                        break
                if has_event and ".set()" in src:
                    continue
                yield _finding(
                    fi, ctor, "L5",
                    f"thread {name or '<unbound>'} started with no join() "
                    "or stop-Event path in this module",
                    "keep a handle and join() it in stop(), or wire a "
                    "stop Event the loop polls")
