"""lightgbm_tpu: a TPU-native gradient-boosting framework.

A from-scratch re-design of the LightGBM capability surface
(reference: xiangyu/LightGBM, fork of microsoft/LightGBM) for TPU hardware:
histogram construction, split search, partitioning and prediction are
JAX/XLA/Pallas programs; distributed training is SPMD over a
jax.sharding.Mesh with XLA collectives instead of the reference's
socket/MPI Network layer.

Public API mirrors python-package/lightgbm/__init__.py.
"""

from .basic import Booster, CorruptModelError, Dataset, LightGBMError, Sequence_ as Sequence
from .callback import EarlyStopException, early_stopping, log_evaluation, record_evaluation, reset_parameter
from . import serve as _serve_pkg
from .continual import ContinualError, ContinualRunner
from .serve import Overloaded, ServingRuntime
from .serve import runtime as _serve_runtime_mod

# NOTE: imported AFTER the serve package so the package attribute
# `lightgbm_tpu.serve` resolves to the entry-point FUNCTION (engine.serve);
# the module itself stays importable as `from lightgbm_tpu.serve import ...`
# (sys.modules resolution is unaffected by the attribute shadowing).
from .engine import CVBooster, continual_train, cv, serve, train, train_fleet
from .models.fleet import FleetBooster, FleetError
from .utils.guards import NonFiniteError
from .utils.log import register_logger

# graft EVERY public name of the subpackage onto the shadowing function —
# driven by its __all__, so a name added there can never be missed here —
# making `import lightgbm_tpu; lightgbm_tpu.serve.ServingRuntime` work
# alongside `lgb.serve(booster)` and `from lightgbm_tpu.serve import ...`
# (both spellings pinned in tests/test_serve.py)
for _name in _serve_pkg.__all__:
    setattr(serve, _name, getattr(_serve_pkg, _name))
serve.runtime = _serve_runtime_mod
del _name, _serve_pkg, _serve_runtime_mod

__all__ = [
    "Dataset",
    "Sequence",
    "Booster",
    "CVBooster",
    "LightGBMError",
    "CorruptModelError",
    "NonFiniteError",
    "register_logger",
    "train",
    "train_fleet",
    "FleetBooster",
    "FleetError",
    "cv",
    "serve",
    "ServingRuntime",
    "Overloaded",
    "continual_train",
    "ContinualRunner",
    "ContinualError",
    "early_stopping",
    "log_evaluation",
    "record_evaluation",
    "reset_parameter",
    "EarlyStopException",
]

__version__ = "0.1.0"

try:  # sklearn wrappers are optional at import time (mirrors compat.py)
    from .sklearn import LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor  # noqa: F401

    __all__ += ["LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"]
except ImportError:  # pragma: no cover
    pass

try:  # distributed estimators (reference: lightgbm.dask exposes DaskLGBM*)
    from .dask import (  # noqa: F401
        DaskLGBMClassifier,
        DaskLGBMRanker,
        DaskLGBMRegressor,
    )

    __all__ += ["DaskLGBMClassifier", "DaskLGBMRegressor", "DaskLGBMRanker"]
except ImportError:  # pragma: no cover
    pass

# plotting imports matplotlib/graphviz only at call time, so the module
# itself is always importable
from .plotting import (  # noqa: F401
    create_tree_digraph,
    plot_importance,
    plot_metric,
    plot_split_value_histogram,
    plot_tree,
)

__all__ += [
    "plot_importance",
    "plot_split_value_histogram",
    "plot_metric",
    "plot_tree",
    "create_tree_digraph",
]
