"""lightgbm_tpu: a TPU-native gradient-boosting framework.

A from-scratch re-design of the LightGBM capability surface
(reference: xiangyu/LightGBM, fork of microsoft/LightGBM) for TPU hardware:
histogram construction, split search, partitioning and prediction are
JAX/XLA/Pallas programs; distributed training is SPMD over a
jax.sharding.Mesh with XLA collectives instead of the reference's
socket/MPI Network layer.

Public API mirrors python-package/lightgbm/__init__.py.
"""

from .basic import Booster, CorruptModelError, Dataset, LightGBMError, Sequence_ as Sequence
from .callback import EarlyStopException, early_stopping, log_evaluation, record_evaluation, reset_parameter
from .engine import CVBooster, cv, train
from .utils.guards import NonFiniteError
from .utils.log import register_logger

__all__ = [
    "Dataset",
    "Sequence",
    "Booster",
    "CVBooster",
    "LightGBMError",
    "CorruptModelError",
    "NonFiniteError",
    "register_logger",
    "train",
    "cv",
    "early_stopping",
    "log_evaluation",
    "record_evaluation",
    "reset_parameter",
    "EarlyStopException",
]

__version__ = "0.1.0"

try:  # sklearn wrappers are optional at import time (mirrors compat.py)
    from .sklearn import LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor  # noqa: F401

    __all__ += ["LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"]
except ImportError:  # pragma: no cover
    pass

try:  # distributed estimators (reference: lightgbm.dask exposes DaskLGBM*)
    from .dask import (  # noqa: F401
        DaskLGBMClassifier,
        DaskLGBMRanker,
        DaskLGBMRegressor,
    )

    __all__ += ["DaskLGBMClassifier", "DaskLGBMRegressor", "DaskLGBMRanker"]
except ImportError:  # pragma: no cover
    pass

# plotting imports matplotlib/graphviz only at call time, so the module
# itself is always importable
from .plotting import (  # noqa: F401
    create_tree_digraph,
    plot_importance,
    plot_metric,
    plot_split_value_histogram,
    plot_tree,
)

__all__ += [
    "plot_importance",
    "plot_split_value_histogram",
    "plot_metric",
    "plot_tree",
    "create_tree_digraph",
]
