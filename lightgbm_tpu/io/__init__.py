from .parser import load_data_file, parse_text  # noqa: F401
