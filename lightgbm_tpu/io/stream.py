"""Out-of-core streaming of ``save_binary`` caches (docs round 12).

``Dataset.save_binary`` writes an npz (zip) whose ``bins`` member is the
full (N, F) binned matrix.  ``np.load`` materializes that member whole —
at Higgs-11M x 2000-feature scale the one array is tens of GB, which is
exactly what the out-of-core path must never do.  This module reads the
member the way the reference's two-round loader reads text files:
SEQUENTIALLY, in row chunks, through one reused host buffer.

Key facts the implementation leans on:

* an ``.npy`` payload is a fixed-size header followed by the raw
  C-order element bytes — row ``i`` starts at ``i * F * itemsize``, so
  a sequential read yields whole row chunks with no deserialization;
* a zip member (stored OR deflated) supports streaming reads via
  ``zipfile.ZipFile.open`` — no random access needed, because every
  consumer here sweeps rows front-to-back (ingest fills the device
  matrix once; the spill grower's histogram passes are full sweeps);
* the chunk buffer is allocated ONCE per stream and refilled in place
  (``readinto``) — the "pinned, reused host buffers" contract: steady-
  state streaming does zero per-chunk allocation on the host side.

:class:`BinCacheStream` is the file-backed source; :func:`array_chunks`
is the same protocol over an in-memory matrix (host-RAM datasets whose
DEVICE residency is capped still stream chunk-wise);
:func:`prefetch_device` overlaps the NEXT chunk's host read + device
upload with the consumer's compute on the CURRENT chunk (JAX uploads
are async — enqueueing chunk k+1 before chunk k's consumer dispatches
keeps the copy engine busy without any blocking sync, the round-7
pipelining discipline applied to the data feed).
"""

from __future__ import annotations

import ast
import zipfile
from typing import Iterator, Optional, Tuple

import numpy as np

DEFAULT_CHUNK_ROWS = 65536


def _read_npy_header(fh) -> Tuple[tuple, np.dtype, bool]:
    """Parse an .npy stream's header: (shape, dtype, fortran_order).
    Reads exactly the header bytes, leaving the stream at element 0."""
    magic = fh.read(6)
    if magic != b"\x93NUMPY":
        raise ValueError("not an .npy stream (bad magic)")
    major, _minor = fh.read(1)[0], fh.read(1)[0]
    if major == 1:
        hlen = int.from_bytes(fh.read(2), "little")
    else:
        hlen = int.from_bytes(fh.read(4), "little")
    header = ast.literal_eval(fh.read(hlen).decode("latin1"))
    return (tuple(header["shape"]), np.dtype(header["descr"]),
            bool(header["fortran_order"]))


class BinCacheStream:
    """Chunked sequential reader of one array member of a save_binary npz.

    ``shape``/``dtype`` come from the member header without reading the
    payload.  :meth:`chunks` yields ``(row_lo, view)`` pairs where
    ``view`` is a window into the SAME reused buffer — consumers must
    copy (device upload copies) before advancing.  Re-iterable: each
    :meth:`chunks` call reopens the member (a fresh sequential
    decompress — the out-of-core price for a full pass)."""

    def __init__(self, path: str, member: str = "bins") -> None:
        self.path = path
        self.member = member + ".npy"
        with zipfile.ZipFile(path) as zf, zf.open(self.member) as fh:
            shape, dtype, fortran = _read_npy_header(fh)
        if fortran or len(shape) != 2:
            raise ValueError(
                f"{path}:{self.member} must be a C-order 2-D array for row "
                f"streaming (shape={shape}, fortran={fortran})")
        self.shape = shape
        self.dtype = dtype

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    @property
    def n_cols(self) -> int:
        return self.shape[1]

    def chunks(self, chunk_rows: int) -> Iterator[Tuple[int, np.ndarray]]:
        """Sequential (row_lo, chunk_view) sweep; the view aliases one
        reused buffer of ``chunk_rows`` rows (allocated once here)."""
        n, f = self.shape
        chunk_rows = max(int(chunk_rows), 1)
        buf = np.empty((chunk_rows, f), self.dtype)  # the reused buffer
        flat = buf.reshape(-1).view(np.uint8)
        row_bytes = f * self.dtype.itemsize
        with zipfile.ZipFile(self.path) as zf, zf.open(self.member) as fh:
            _read_npy_header(fh)  # skip to element 0
            lo = 0
            while lo < n:
                m = min(chunk_rows, n - lo)
                want = m * row_bytes
                got = 0
                mv = memoryview(flat)[:want]
                while got < want:
                    k = fh.readinto(mv[got:])
                    if not k:
                        raise EOFError(
                            f"{self.path}:{self.member} truncated at row "
                            f"{lo + got // row_bytes}")
                    got += k
                yield lo, buf[:m]
                lo += m


def array_chunks(arr: np.ndarray,
                 chunk_rows: int) -> Iterator[Tuple[int, np.ndarray]]:
    """The BinCacheStream protocol over an in-memory matrix: row-chunk
    views, zero copies (numpy slices of a C-order array are views)."""
    n = arr.shape[0]
    chunk_rows = max(int(chunk_rows), 1)
    for lo in range(0, n, chunk_rows):
        yield lo, arr[lo:lo + chunk_rows]


def prefetch_device(chunks: Iterator[Tuple[int, np.ndarray]],
                    dtype=None,
                    pad_rows: Optional[int] = None,
                    ) -> Iterator[Tuple[int, int, "object"]]:
    """One-deep prefetch pipeline: upload chunk k+1 to device while the
    consumer computes on chunk k.

    Yields ``(row_lo, valid_rows, device_chunk)``.  With ``pad_rows``
    every device chunk is padded (zero rows) to that fixed row count so
    downstream jitted consumers see ONE shape — one compile for the
    whole sweep; ``valid_rows`` masks the tail.  The upload of the next
    chunk is enqueued BEFORE the current one is yielded: JAX host->device
    transfers are async, so the copy engine overlaps the consumer's
    dispatches instead of serializing after them (the data-feed analogue
    of the windowed driver's one-round-deep pipeline; jaxlint R9: no
    timing is read here, nothing syncs).
    """
    import jax.numpy as jnp

    pad_buf = None

    def _upload(lo: int, view: np.ndarray):
        nonlocal pad_buf
        m = view.shape[0]
        if pad_rows is not None and m < pad_rows:
            if pad_buf is None:
                pad_buf = np.zeros((pad_rows, view.shape[1]), view.dtype)
            pad_buf[:m] = view
            pad_buf[m:] = 0
            host = pad_buf
        else:
            host = view
        # copy=True: the CPU backend can share a numpy buffer zero-copy,
        # and `host` aliases a REUSED staging buffer that the next chunk
        # refills — an aliased upload would corrupt the in-flight chunk
        dev = jnp.array(host, dtype=dtype, copy=True)
        return lo, m, dev

    prev = None
    for lo, view in chunks:
        cur = _upload(lo, view)
        if prev is not None:
            yield prev
        prev = cur
    if prev is not None:
        yield prev
