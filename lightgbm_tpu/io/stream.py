"""Out-of-core streaming of ``save_binary`` caches (docs round 12).

``Dataset.save_binary`` writes an npz (zip) whose ``bins`` member is the
full (N, F) binned matrix.  ``np.load`` materializes that member whole —
at Higgs-11M x 2000-feature scale the one array is tens of GB, which is
exactly what the out-of-core path must never do.  This module reads the
member the way the reference's two-round loader reads text files:
SEQUENTIALLY, in row chunks, through one reused host buffer.

Key facts the implementation leans on:

* an ``.npy`` payload is a fixed-size header followed by the raw
  C-order element bytes — row ``i`` starts at ``i * F * itemsize``, so
  a sequential read yields whole row chunks with no deserialization;
* a zip member (stored OR deflated) supports streaming reads via
  ``zipfile.ZipFile.open`` — no random access needed, because every
  consumer here sweeps rows front-to-back (ingest fills the device
  matrix once; the spill grower's histogram passes are full sweeps);
* the chunk buffer is allocated ONCE per stream and refilled in place
  (``readinto``) — the "pinned, reused host buffers" contract: steady-
  state streaming does zero per-chunk allocation on the host side.

:class:`BinCacheStream` is the file-backed source; :func:`array_chunks`
is the same protocol over an in-memory matrix (host-RAM datasets whose
DEVICE residency is capped still stream chunk-wise);
:func:`prefetch_device` overlaps the NEXT chunk's host read + device
upload with the consumer's compute on the CURRENT chunk (JAX uploads
are async — enqueueing chunk k+1 before chunk k's consumer dispatches
keeps the copy engine busy without any blocking sync, the round-7
pipelining discipline applied to the data feed).
"""

from __future__ import annotations

import ast
import io as _io
import os
import tempfile
import zipfile
import zlib
from typing import Iterator, List, Optional, Tuple

import numpy as np

DEFAULT_CHUNK_ROWS = 65536

# fixed CRC32 block size for save_binary caches (rows per CRC entry) —
# independent of the READ chunk size, so any sweep granularity verifies
# against the same trailer table
DEFAULT_CRC_ROWS = 65536


class CorruptBinCacheError(RuntimeError):
    """A ``save_binary`` cache failed integrity verification while
    streaming: a per-chunk CRC32 mismatch, a truncated member, or a
    decompression failure.  Carries the failing CRC chunk and its row
    range, so the error names WHERE the cache is bad instead of letting
    training proceed on garbage bins."""

    def __init__(self, path: str, member: str, chunk_index: int,
                 row_lo: int, row_hi: int, reason: str):
        super().__init__(
            f"{path}:{member} is corrupt at CRC chunk {chunk_index} "
            f"(rows [{row_lo}, {row_hi})): {reason} — the bin cache is "
            "torn or bit-rotted; rebuild it with save_binary "
            "(docs/ROBUSTNESS.md)")
        self.path = path
        self.member = member
        self.chunk_index = chunk_index
        self.row_lo = row_lo
        self.row_hi = row_hi


def bin_crc32s(bins: np.ndarray,
               crc_rows: int = DEFAULT_CRC_ROWS) -> np.ndarray:
    """Per-block CRC32 table over a C-order 2-D binned matrix — the
    values ``save_binary`` stores next to the matrix and
    :class:`BinCacheStream` verifies on read."""
    bins = np.ascontiguousarray(bins)
    crc_rows = max(int(crc_rows), 1)
    out = [zlib.crc32(bins[lo:lo + crc_rows]) & 0xFFFFFFFF
           for lo in range(0, bins.shape[0], crc_rows)]
    return np.asarray(out, np.uint32)


def _read_npy_header(fh) -> Tuple[tuple, np.dtype, bool]:
    """Parse an .npy stream's header: (shape, dtype, fortran_order).
    Reads exactly the header bytes, leaving the stream at element 0."""
    magic = fh.read(6)
    if magic != b"\x93NUMPY":
        raise ValueError("not an .npy stream (bad magic)")
    major, _minor = fh.read(1)[0], fh.read(1)[0]
    if major == 1:
        hlen = int.from_bytes(fh.read(2), "little")
    else:
        hlen = int.from_bytes(fh.read(4), "little")
    header = ast.literal_eval(fh.read(hlen).decode("latin1"))
    return (tuple(header["shape"]), np.dtype(header["descr"]),
            bool(header["fortran_order"]))


class BinCacheStream:
    """Chunked sequential reader of one array member of a save_binary npz.

    ``shape``/``dtype`` come from the member header without reading the
    payload.  :meth:`chunks` yields ``(row_lo, view)`` pairs where
    ``view`` is a window into the SAME reused buffer — consumers must
    copy (device upload copies) before advancing.  Re-iterable: each
    :meth:`chunks` call reopens the member (a fresh sequential
    decompress — the out-of-core price for a full pass).

    ``shard=(row_lo, row_hi)`` restricts the stream to that row range —
    the rank-sharded form for distributed out-of-core training: each
    rank streams ONLY its shard of one shared cache (the fleet manifest
    already fingerprints per-rank shards, docs/ROBUSTNESS.md), paying a
    seek instead of a whole-prefix decompress on the stored (default
    ``save_binary``) members.  ``chunks`` then yields GLOBAL row_lo
    values within [row_lo, row_hi); CRC32 blocks are verified whenever
    the stream covers them from their true start — blocks cut by a shard
    boundary cannot be (their prefix bytes were never read) and are
    skipped, so a whole-cache sweep still verifies everything while a
    shard sweep verifies every fully-covered block."""

    def __init__(self, path: str, member: str = "bins",
                 shard: Optional[Tuple[int, int]] = None) -> None:
        self.path = path
        self.member = member + ".npy"
        try:
            with zipfile.ZipFile(path) as zf, zf.open(self.member) as fh:
                shape, dtype, fortran = _read_npy_header(fh)
        except (zipfile.BadZipFile, zlib.error) as e:
            # small stored members are CRC-checked whole by zipfile on the
            # very first read: surface the same typed row-ranged error the
            # sweep path raises instead of a raw BadZipFile
            raise CorruptBinCacheError(
                path, self.member, 0, 0, 0,
                f"{type(e).__name__}: {e}") from None
        if fortran or len(shape) != 2:
            raise ValueError(
                f"{path}:{self.member} must be a C-order 2-D array for row "
                f"streaming (shape={shape}, fortran={fortran})")
        self.shape = shape
        self.dtype = dtype
        # base-member row extent — live append SEGMENTS (round 22,
        # sidecar `<path>.seg.<k>` files) ride BEHIND it in the logical
        # row space; self.shape grows to cover them below
        self._base_rows = int(shape[0])
        # per-chunk CRC trailer table (written by save_binary since round
        # 13).  Old trailerless caches still load — with a warning, since
        # nothing can vouch for their bytes.
        self.crc_rows: Optional[int] = None
        self.crcs: Optional[np.ndarray] = None
        # append-origin log (round 19, continual ingest): global row
        # offsets where each append_rows() call began, so a row-ranged
        # corruption error can NAME the appended chunk it falls in
        self.append_log: Optional[np.ndarray] = None
        # compaction watermark (round 22): segment indices <= watermark
        # are already folded into the base member — a stale sidecar left
        # by a crash between the compaction's atomic replace and its
        # segment deletes is IGNORED, never double-counted
        self.seg_watermark = -1
        try:
            with np.load(path, allow_pickle=False) as z:
                if (f"{member}_crc32" in z.files
                        and f"{member}_crc_rows" in z.files):
                    self.crcs = np.asarray(z[f"{member}_crc32"], np.uint32)
                    self.crc_rows = max(
                        int(np.asarray(z[f"{member}_crc_rows"]).reshape(-1)[0]),
                        1)
                if f"{member}_append_rows" in z.files:
                    self.append_log = np.asarray(
                        z[f"{member}_append_rows"], np.int64)
                if f"{member}_seg_watermark" in z.files:
                    self.seg_watermark = int(np.asarray(
                        z[f"{member}_seg_watermark"]).reshape(-1)[0])
        except (OSError, ValueError, zipfile.BadZipFile):
            pass  # chunk reads will surface real corruption row-ranged
        # live segments: each is itself a mini bin cache (bins + CRC
        # table + label/weight), so a nested stream verifies it with the
        # SAME machinery.  Segment files are never themselves segmented
        # (append_rows only writes sidecars next to the base path).
        self.segments: List[Tuple[int, str, int]] = []  # (k, path, rows)
        if member == "bins":
            n_total = self._base_rows
            starts: List[int] = []
            for k, sp in _live_segments(path, self.seg_watermark):
                sub = BinCacheStream(sp)
                if (sub.shape[1] != shape[1] or sub.dtype != self.dtype):
                    raise CorruptBinCacheError(
                        sp, "bins.npy", 0, 0, sub.shape[0],
                        f"segment shape {sub.shape}/{sub.dtype} does not "
                        f"match base cache {shape}/{self.dtype}")
                starts.append(n_total)
                self.segments.append((k, sp, sub.shape[0]))
                n_total += sub.shape[0]
            if self.segments:
                self.shape = (n_total, shape[1])
                base_log = (np.asarray(self.append_log, np.int64)
                            if self.append_log is not None
                            else np.zeros(0, np.int64))
                self.append_log = np.concatenate(
                    [base_log, np.asarray(starts, np.int64)])
        if shard is not None:
            lo, hi = int(shard[0]), int(shard[1])
            if not (0 <= lo < hi <= self.shape[0]):
                raise ValueError(
                    f"shard range [{lo}, {hi}) is outside the cache's "
                    f"{self.shape[0]} rows")
            self.shard = (lo, hi)
        else:
            self.shard = None
        if self.crcs is not None:
            expect = (-(-self._base_rows // self.crc_rows)
                      if self._base_rows else 0)
            if len(self.crcs) != expect:
                raise CorruptBinCacheError(
                    path, self.member, 0, 0, min(self.crc_rows,
                                                 self._base_rows),
                    f"CRC table has {len(self.crcs)} entries, "
                    f"expected {expect}")
        else:
            from ..utils.log import log_warning

            log_warning(
                f"bin cache {path} carries no per-chunk CRC trailers "
                "(pre-round-13 format): reads cannot be verified against "
                "bit-rot — re-run save_binary to upgrade it")

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    @property
    def shard_rows(self) -> int:
        """Rows this stream actually yields (== n_rows without a shard)."""
        if self.shard is None:
            return self.shape[0]
        return self.shard[1] - self.shard[0]

    @property
    def n_cols(self) -> int:
        return self.shape[1]

    def _corrupt(self, row: int, reason: str) -> CorruptBinCacheError:
        crc_rows = self.crc_rows or DEFAULT_CRC_ROWS
        chunk = row // crc_rows
        if self.append_log is not None and len(self.append_log):
            # name the appended chunk the bad row falls in: the newest
            # append whose start row is <= the failing row (rows before
            # the first append are the original save_binary payload)
            starts = np.asarray(self.append_log, np.int64)
            k = int(np.searchsorted(starts, row, side="right")) - 1
            if k >= 0:
                reason += (f" (inside appended chunk {k} — append_rows() "
                           f"call starting at row {int(starts[k])})")
            else:
                reason += " (inside the original pre-append payload)"
        return CorruptBinCacheError(
            self.path, self.member, chunk, chunk * crc_rows,
            min((chunk + 1) * crc_rows, self.shape[0]), reason)

    def chunks(self, chunk_rows: int) -> Iterator[Tuple[int, np.ndarray]]:
        """Sequential (row_lo, chunk_view) sweep; the view aliases one
        reused buffer of ``chunk_rows`` rows (allocated once here).

        Every sweep re-verifies the per-chunk CRC32 table when the cache
        carries one: the rolling CRC is checked at each CRC-block
        boundary BEFORE the rows completing the block are yielded, so a
        corrupt or truncated cache raises the row-ranged
        :class:`CorruptBinCacheError` at the failing chunk instead of
        feeding garbage bins to training.  (With the default read chunk
        == CRC block size, no unverified row is ever yielded; smaller
        read chunks may see at most one partially-verified trailing
        block's rows before its boundary check runs.)

        With a ``shard`` the sweep covers only [row_lo, row_hi): the
        member is seeked to row_lo (stored members skip the prefix
        without decompressing it) and blocks the shard enters mid-way
        are skipped by verification, never trusted blind — a corrupt
        byte inside any FULLY covered block still raises row-ranged.

        Live append segments ride transparently: the sweep covers the
        base member, then each segment in index order, with GLOBAL row
        offsets — each segment verifies against its OWN CRC table
        through a nested stream."""
        lo0, hi0 = self.shard if self.shard is not None else (0,
                                                              self.shape[0])
        nb = self._base_rows
        if lo0 < nb:
            yield from self._base_chunks(chunk_rows, lo0, min(hi0, nb))
        off = nb
        for _k, sp, n_seg in self.segments:
            s_lo, s_hi = max(lo0 - off, 0), min(hi0 - off, n_seg)
            if s_lo < s_hi:
                sub = BinCacheStream(
                    sp, shard=((s_lo, s_hi) if (s_lo, s_hi) != (0, n_seg)
                               else None))
                for seg_lo, view in sub.chunks(chunk_rows):
                    yield off + seg_lo, view
            off += n_seg

    def _base_chunks(self, chunk_rows: int, lo0: int,
                     hi0: int) -> Iterator[Tuple[int, np.ndarray]]:
        """The base-member sweep over rows [lo0, hi0) — the pre-segment
        chunks() body, with the row range parameterized so the composed
        sweep can clip it to the base extent."""
        n, f = self._base_rows, self.shape[1]
        chunk_rows = max(int(chunk_rows), 1)
        buf = np.empty((chunk_rows, f), self.dtype)  # the reused buffer
        flat = buf.reshape(-1).view(np.uint8)
        row_bytes = f * self.dtype.itemsize
        verify = self.crcs is not None
        crc_cur = 0  # rolling CRC of the current (partial) CRC block
        # a shard entering a CRC block mid-way cannot verify it (the
        # block's leading bytes were never read); arm from the first
        # block the shard covers from its true start
        crc_valid = verify and (not lo0 or lo0 % self.crc_rows == 0)
        with zipfile.ZipFile(self.path) as zf, zf.open(self.member) as fh:
            _read_npy_header(fh)  # skip to element 0
            if lo0:
                try:
                    fh.seek(fh.tell() + lo0 * row_bytes)
                except (OSError, zipfile.BadZipFile, zlib.error) as e:
                    raise self._corrupt(
                        lo0, f"seek to shard start failed: "
                        f"{type(e).__name__}: {e}") from None
            lo = lo0
            while lo < hi0:
                m = min(chunk_rows, hi0 - lo)
                want = m * row_bytes
                got = 0
                mv = memoryview(flat)[:want]
                while got < want:
                    try:
                        k = fh.readinto(mv[got:])
                    except (zipfile.BadZipFile, zlib.error, OSError) as e:
                        raise self._corrupt(
                            lo + got // row_bytes,
                            f"{type(e).__name__}: {e}") from None
                    if not k:
                        raise self._corrupt(lo + got // row_bytes,
                                            "truncated member")
                    got += k
                if verify:
                    # feed the freshly read rows into the rolling CRC,
                    # checking every block boundary they complete
                    pos, row, end_row = 0, lo, lo + m
                    while row < end_row:
                        block = row // self.crc_rows
                        block_end = min((block + 1) * self.crc_rows, n)
                        take = min(block_end, end_row) - row
                        if crc_valid:
                            crc_cur = zlib.crc32(
                                mv[pos:pos + take * row_bytes], crc_cur)
                        pos += take * row_bytes
                        row += take
                        if row == block_end:
                            if crc_valid and (crc_cur & 0xFFFFFFFF) != int(
                                    self.crcs[block]):
                                raise self._corrupt(block_end - 1,
                                                    "CRC32 mismatch")
                            crc_cur = 0
                            crc_valid = verify  # past the shard's cut
                            # block, every block starts from its true head
                yield lo, buf[:m]
                lo += m


def read_cache_shard(path: str, row_lo: int, row_hi: int,
                     chunk_rows: int = DEFAULT_CHUNK_ROWS,
                     member: str = "bins") -> np.ndarray:
    """Materialize rows [row_lo, row_hi) of a save_binary cache through
    the shard-restricted stream: one reused read buffer, every CRC block
    the shard fully covers verified row-ranged — the launcher's
    pre-partition worker feed (docs/DISTRIBUTED.md "Hierarchical
    merge"): each rank reads ONLY its shard of one shared cache instead
    of every rank decompressing the full matrix."""
    st = BinCacheStream(path, member=member, shard=(int(row_lo),
                                                    int(row_hi)))
    out = np.empty((st.shard_rows, st.n_cols), st.dtype)
    base = int(row_lo)
    for lo, view in st.chunks(chunk_rows):
        out[lo - base: lo - base + view.shape[0]] = view
    return out


def cache_shard_fingerprint(path: str, row_lo: int, row_hi: int,
                            member: str = "bins") -> str:
    """Stable sha256 identity of rows [row_lo, row_hi) of a cache,
    derived from the header + the CRC trailer table entries overlapping
    the range — cheap (no payload read) and byte-change-sensitive, the
    per-rank data fingerprint the fleet manifests stamp for the cache
    worker feed.  Legacy trailerless caches return "" (nothing can vouch
    for their bytes; the resume guard skips empty fingerprints)."""
    import hashlib

    st = BinCacheStream(path, member=member)
    if st.crcs is None:
        return ""
    lo_b = int(row_lo) // st.crc_rows
    hi_b = -(-min(int(row_hi), st._base_rows) // st.crc_rows)
    h = hashlib.sha256()
    h.update(repr((st.shape, str(st.dtype), int(row_lo),
                   int(row_hi))).encode())
    h.update(np.ascontiguousarray(st.crcs[lo_b:hi_b]).tobytes())
    # live segments overlapping the range contribute their OWN CRC
    # entries (plus identity), so the fingerprint moves whenever any
    # covered byte does — base or sidecar
    off = st._base_rows
    for k, sp, n_seg in st.segments:
        s_lo = max(int(row_lo) - off, 0)
        s_hi = min(int(row_hi) - off, n_seg)
        if s_lo < s_hi:
            sub = BinCacheStream(sp)
            if sub.crcs is None:
                return ""  # unverifiable segment: nothing can vouch
            h.update(repr((k, sub.shape, s_lo, s_hi)).encode())
            h.update(np.ascontiguousarray(
                sub.crcs[s_lo // sub.crc_rows:
                         -(-s_hi // sub.crc_rows)]).tobytes())
        off += n_seg
    return h.hexdigest()


# ---------------------------------------------------------------------------
# append-able caches (round 19, continual ingest — docs/README "Continuous
# training"): save_binary caches grow in place through append_rows(), so a
# live trainer can keep CRC-verified durable ingest without ever holding
# the whole matrix.  The write is a streamed REWRITE (zip members cannot
# be extended in place): the old payload is swept once through the same
# verified BinCacheStream path every training sweep uses — so appending to
# a corrupt cache fails row-ranged BEFORE the atomic replace, and the old
# file survives intact — and the fresh CRC table covers every row, old and
# new.  Appending to a LEGACY (trailerless) cache UPGRADES it: the sweep
# is the one moment every old byte passes through host memory anyway, so
# the new file always carries a full table instead of silently mixing
# verified new blocks with unverifiable old ones.
# ---------------------------------------------------------------------------


class _CrcTableBuilder:
    """Rolling per-block CRC32 over a row stream (the bin_crc32s layout,
    fed incrementally so the appended cache's table is computed in the
    same single sweep that writes the payload)."""

    def __init__(self, crc_rows: int, row_bytes: int):
        self.crc_rows = max(int(crc_rows), 1)
        self.row_bytes = int(row_bytes)
        self._crc = 0
        self._rows_in_block = 0
        self._table: List[int] = []

    def feed(self, data, n_rows: int) -> None:
        mv = memoryview(data)
        pos = 0
        while n_rows:
            take = min(self.crc_rows - self._rows_in_block, n_rows)
            self._crc = zlib.crc32(mv[pos:pos + take * self.row_bytes],
                                   self._crc)
            pos += take * self.row_bytes
            self._rows_in_block += take
            n_rows -= take
            if self._rows_in_block == self.crc_rows:
                self._table.append(self._crc & 0xFFFFFFFF)
                self._crc = 0
                self._rows_in_block = 0

    def finish(self) -> np.ndarray:
        if self._rows_in_block:
            self._table.append(self._crc & 0xFFFFFFFF)
            self._crc = 0
            self._rows_in_block = 0
        return np.asarray(self._table, np.uint32)


def _npy_member_bytes(arr: np.ndarray) -> bytes:
    """Full .npy byte payload for a small array member."""
    bio = _io.BytesIO()
    np.save(bio, np.ascontiguousarray(arr), allow_pickle=False)
    return bio.getvalue()


def _write_streamed_bins(zf: zipfile.ZipFile, member: str,
                         n_rows: int, n_cols: int, dtype: np.dtype,
                         chunks: Iterator[Tuple[int, np.ndarray]],
                         crc: _CrcTableBuilder) -> None:
    """Write ``member`` (an .npy of (n_rows, n_cols) ``dtype``) into an
    open zip by streaming row chunks — the matrix is never materialized
    whole, the out-of-core contract this module exists for.  ZIP_STORED,
    so shard seeks on the result stay O(1)."""
    zinfo = zipfile.ZipInfo(member)
    zinfo.compress_type = zipfile.ZIP_STORED
    header = _io.BytesIO()
    np.lib.format.write_array_header_1_0(
        header, {"descr": np.lib.format.dtype_to_descr(np.dtype(dtype)),
                 "fortran_order": False, "shape": (int(n_rows), int(n_cols))})
    with zf.open(zinfo, "w", force_zip64=True) as out:
        out.write(header.getvalue())
        for _lo, view in chunks:
            block = np.ascontiguousarray(view, dtype=dtype)
            data = block.reshape(-1).view(np.uint8).data
            out.write(data)
            crc.feed(data, block.shape[0])


def write_bin_cache(fh, bins: np.ndarray, mappers, *,
                    label=None, weight=None, group=None, init_score=None,
                    position=None, feature_names=(),
                    crc_rows: int = DEFAULT_CRC_ROWS) -> None:
    """The save_binary npz payload (Dataset._savez_binary delegates here;
    the continual runner also creates fresh ingest caches through it
    without needing a Dataset).  ``mappers`` is a DatasetBinner-style
    mapper list; the per-chunk CRC32 trailer table always rides along."""
    bins_c = np.ascontiguousarray(bins)
    np.savez_compressed(
        fh,
        bins=bins_c,
        bins_crc32=bin_crc32s(bins_c, crc_rows),
        bins_crc_rows=np.asarray(crc_rows, np.int64),
        label=label if label is not None else np.zeros(0),
        weight=weight if weight is not None else np.zeros(0),
        group=group if group is not None else np.zeros(0, np.int64),
        init_score=init_score if init_score is not None else np.zeros(0),
        position=position if position is not None else np.zeros(0, np.int64),
        uppers=np.concatenate([np.asarray(m.upper_bounds, np.float64)
                               for m in mappers]),
        upper_sizes=np.asarray([len(m.upper_bounds) for m in mappers]),
        missing_types=np.asarray([m.missing_type for m in mappers]),
        cats=np.concatenate([
            np.asarray(m.categories, np.float64)
            if m.categories is not None else np.zeros(0) for m in mappers]),
        cat_sizes=np.asarray([
            len(m.categories) if m.categories is not None else 0
            for m in mappers]),
        min_values=np.asarray([m.min_value for m in mappers], np.float64),
        max_values=np.asarray([m.max_value for m in mappers], np.float64),
        feature_names=np.asarray(feature_names),
    )


def _atomic_replace(path: str, write_fn, mode: int) -> None:
    """The ONE binary crash-safety scaffold (same-dir temp + explicit
    permissions + fsync AFTER ``write_fn`` returns + ``os.replace``):
    :func:`create_bin_cache` and :func:`append_rows` both ride it, so
    the recipe cannot drift between the create and append halves
    (utils/checkpoint.py owns the separate text+trailer variant).
    ``write_fn(fh)`` must fully CLOSE any framing it opens (e.g. a
    ZipFile's central directory) before returning — the fsync here is
    the last write barrier before publication."""
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".tmp.",
                               dir=d)
    try:
        os.fchmod(fd, mode)
        with os.fdopen(fd, "wb") as fh:
            write_fn(fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _umask_mode() -> int:
    """0o666 under the current umask — what a plain open()-write would
    create (shared dirs, serving processes under another uid; the same
    rule utils/checkpoint.py's atomic writer applies)."""
    umask = os.umask(0)
    os.umask(umask)
    return 0o666 & ~umask


def create_bin_cache(path: str, bins: np.ndarray, mappers, **kw) -> None:
    """Atomically CREATE a save_binary cache at ``path``: the
    creation-side counterpart of :func:`append_rows`'s crash contract —
    a crash mid-write must not leave a torn cache that poisons every
    later append.  ``kw`` forwards to :func:`write_bin_cache`."""
    _atomic_replace(path, lambda fh: write_bin_cache(fh, bins, mappers,
                                                     **kw),
                    _umask_mode())


# members append_rows recomputes; everything else (mappers, group,
# init_score, position, names) is byte-copied verbatim from the old zip
_APPEND_REWRITTEN = ("bins.npy", "bins_crc32.npy", "bins_crc_rows.npy",
                     "bins_append_rows.npy", "bins_seg_watermark.npy",
                     "label.npy", "weight.npy")


def _seg_path(path: str, k: int) -> str:
    return f"{path}.seg.{k}"


def _live_segments(path: str, watermark: int) -> List[Tuple[int, str]]:
    """Sidecar segment files of ``path`` NOT yet folded into the base
    (index past the compaction watermark), in index order.  A cheap
    directory scan — no payload reads."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    prefix = os.path.basename(path) + ".seg."
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in names:
        if not name.startswith(prefix):
            continue
        tail = name[len(prefix):]
        if not tail.isdigit():
            continue  # temp files from an in-flight atomic write
        k = int(tail)
        if k > watermark:
            out.append((k, os.path.join(d, name)))
    out.sort()
    return out


def _cache_row_meta(path: str, stream: "BinCacheStream"):
    """(label, weight, group, init_score, position) across the base npz
    AND its live segments — the concatenated per-row metadata a rewrite
    or materialized load must carry (group/init/position never ride
    segments: appends refuse those caches outright)."""
    with np.load(path, allow_pickle=False) as z:
        label = z["label"] if "label" in z.files else np.zeros(0)
        weight = z["weight"] if "weight" in z.files else np.zeros(0)
        group = z["group"] if "group" in z.files else np.zeros(0)
        init = z["init_score"] if "init_score" in z.files else np.zeros(0)
        pos = z["position"] if "position" in z.files else np.zeros(0)
    labels, weights = [np.asarray(label, np.float64)], [
        np.asarray(weight, np.float64)]
    for _k, sp, _n in stream.segments:
        with np.load(sp, allow_pickle=False) as z:
            if "label" in z.files and z["label"].size:
                labels.append(np.asarray(z["label"], np.float64))
            if "weight" in z.files and z["weight"].size:
                weights.append(np.asarray(z["weight"], np.float64))
    return (np.concatenate(labels), np.concatenate(weights),
            group, init, pos)


def _validate_append(path: str, stream: "BinCacheStream", bins_new,
                    label, weight):
    """Shared admission checks for both append modes.  Returns
    (bins_new_contig, label_f64_or_None, weight_f64_or_None,
    old_label, old_weight)."""
    f = stream.shape[1]
    bins_new = np.ascontiguousarray(bins_new)
    if bins_new.ndim != 2 or bins_new.shape[1] != f:
        raise ValueError(
            f"append_rows: appended chunk has shape {bins_new.shape}, "
            f"cache {path} holds {f}-feature rows")
    info = np.iinfo(stream.dtype) if np.issubdtype(stream.dtype, np.integer) \
        else None
    if info is not None and bins_new.size and (
            int(bins_new.max()) > info.max or int(bins_new.min()) < info.min):
        raise ValueError(
            f"append_rows: bin values outside the cache dtype "
            f"{stream.dtype} — the chunk was not binned by this cache's "
            "mappers")
    old_label, old_weight, old_group, old_init, old_pos = _cache_row_meta(
        path, stream)
    if old_group.size or old_init.size or old_pos.size:
        raise ValueError(
            "append_rows: caches carrying group/init_score/position rows "
            "cannot be appended to (per-row metadata would go out of step)")
    n_new = int(bins_new.shape[0])
    if old_label.size:
        if label is None:
            raise ValueError(
                f"append_rows: cache {path} carries labels; the appended "
                "chunk must bring labels too")
        label = np.asarray(label, np.float64).ravel()
        if len(label) != n_new:
            raise ValueError(
                f"append_rows: {n_new} rows but {len(label)} labels")
    elif label is not None:
        raise ValueError(
            f"append_rows: cache {path} carries no labels; appending "
            "labeled rows would leave the original rows unlabeled")
    if old_weight.size:
        if weight is None:
            raise ValueError(
                f"append_rows: cache {path} carries weights; the appended "
                "chunk must bring weights too")
        weight = np.asarray(weight, np.float64).ravel()
        if len(weight) != n_new:
            raise ValueError(
                f"append_rows: {n_new} rows but {len(weight)} weights")
    elif weight is not None:
        raise ValueError(
            f"append_rows: cache {path} carries no weights; appending "
            "weighted rows would leave the original rows unweighted")
    return bins_new, label, weight, old_label, old_weight


def _rewrite_cache(path: str, stream: "BinCacheStream", bins_new,
                   new_label: np.ndarray, new_weight: np.ndarray,
                   append_log: np.ndarray, watermark: int,
                   chunk_rows: int) -> None:
    """Stream base + live segments (+ optionally fresh rows) into a new
    base npz through the ONE atomic-replace scaffold.  Every old byte
    passes the verified chunks() path, so corruption raises row-ranged
    BEFORE the replace; the watermark member marks every folded segment
    index so stale sidecars a crash leaves behind are ignored."""
    n_total = stream.shape[0] + (int(bins_new.shape[0])
                                 if bins_new is not None else 0)
    f = stream.shape[1]
    crc_rows = stream.crc_rows or DEFAULT_CRC_ROWS
    crc = _CrcTableBuilder(crc_rows, f * stream.dtype.itemsize)

    def _write(fh):
        # closing the ZipFile INSIDE the writer is what makes the
        # scaffold's post-writer fsync cover the central directory
        with zipfile.ZipFile(fh, "w", zipfile.ZIP_STORED) as zf:
            # the old payload (base AND segments) sweeps through the
            # VERIFIED stream (chunks() raises row-ranged on corruption
            # — before the replace ever runs), chained with the new
            # rows; one CRC table covers every seam
            def _all_chunks():
                yield from stream.chunks(chunk_rows)
                if bins_new is not None:
                    yield from array_chunks(bins_new, chunk_rows)

            _write_streamed_bins(zf, "bins.npy", n_total, f,
                                 stream.dtype, _all_chunks(), crc)
            zf.writestr("bins_crc32.npy", _npy_member_bytes(crc.finish()))
            zf.writestr("bins_crc_rows.npy",
                        _npy_member_bytes(np.asarray(crc_rows, np.int64)))
            zf.writestr("bins_append_rows.npy",
                        _npy_member_bytes(append_log))
            if watermark >= 0:
                zf.writestr("bins_seg_watermark.npy",
                            _npy_member_bytes(np.asarray(watermark,
                                                         np.int64)))
            zf.writestr("label.npy", _npy_member_bytes(new_label))
            zf.writestr("weight.npy", _npy_member_bytes(new_weight))
            with zipfile.ZipFile(path) as zf_old:
                for name in zf_old.namelist():
                    if name not in _APPEND_REWRITTEN:
                        zf.writestr(name, zf_old.read(name))

    # keep the original cache's permissions: a shared (e.g. 0644,
    # serving process under another uid) cache stays readable after
    # its first append
    _atomic_replace(path, _write, os.stat(path).st_mode & 0o7777)


def append_rows(path: str, bins_new: np.ndarray, *,
                label=None, weight=None,
                chunk_rows: int = DEFAULT_CHUNK_ROWS,
                segment_threshold: Optional[int] = None) -> int:
    """Append binned rows (already transformed by the cache's FROZEN
    mappers) to a save_binary cache, atomically.

    Two modes, both riding the one :func:`_atomic_replace` scaffold:

    * **rewrite** (default, ``segment_threshold`` unset/0) — the old
      payload streams through the CRC-verified :class:`BinCacheStream`
      path into a same-directory temp file, the new rows follow, and
      ``os.replace`` publishes.  Any live segments fold in on the way
      through.  O(total rows) per append, but the cache stays one file.
    * **segment** (``segment_threshold >= 1``) — the new rows land in a
      CRC'd sidecar ``<path>.seg.<k>`` (its OWN atomic replace; the base
      file is untouched), O(new rows) per append — the continual
      runner's steady-state ingest cost.  Once live segments reach the
      threshold, :func:`compact_bin_cache` folds them back into the base
      (the rewrite path), bumping the compaction watermark so sidecars a
      crash strands are ignored, never double-counted.

    A crash anywhere leaves the previous logical cache intact, and a
    corrupt old cache raises the row-ranged :class:`CorruptBinCacheError`
    before anything is replaced.  A legacy trailerless cache is UPGRADED
    to a full CRC table by any rewrite (never a mixed
    verified/unverified file); the append-origin log
    (``bins_append_rows``) records where each append began so later
    corruption errors can name the appended chunk.  Returns the new
    total row count.

    Labels must ride along when the cache carries them (training data and
    targets may never go out of step); ranking caches (non-empty
    ``group``) and init_score/position-carrying caches refuse appends."""
    stream = BinCacheStream(path)
    n_old = stream.shape[0]
    bins_new, label, weight, old_label, old_weight = _validate_append(
        path, stream, bins_new, label, weight)
    n_new = int(bins_new.shape[0])
    from ..obs import metrics as _obs

    if segment_threshold and int(segment_threshold) >= 1:
        k = max([s[0] for s in stream.segments] + [stream.seg_watermark]) + 1
        _write_segment(path, k, bins_new, stream.dtype,
                       stream.crc_rows or DEFAULT_CRC_ROWS,
                       label, weight, chunk_rows)
        _obs.counter("bin_cache_appends_total").inc()
        _obs.counter("bin_cache_appended_rows_total").inc(n_new)
        _obs.counter("bin_cache_segment_appends_total").inc()
        _obs.event("bin_cache_segment_append", path=os.fspath(path),
                   segment=k, rows=n_new, total_rows=n_old + n_new,
                   live_segments=len(stream.segments) + 1)
        if len(stream.segments) + 1 >= int(segment_threshold):
            compact_bin_cache(path, chunk_rows=chunk_rows)
        return n_old + n_new

    upgraded = stream.crcs is None
    new_label = (np.concatenate([old_label, label])
                 if old_label.size else np.zeros(0))
    new_weight = (np.concatenate([old_weight, weight])
                  if old_weight.size else np.zeros(0))
    append_log = np.concatenate([
        (np.asarray(stream.append_log, np.int64)
         if stream.append_log is not None else np.zeros(0, np.int64)),
        np.asarray([n_old], np.int64)])
    folded = [s[0] for s in stream.segments]
    watermark = max(folded + [stream.seg_watermark])
    _rewrite_cache(path, stream, bins_new, new_label, new_weight,
                   append_log, watermark, chunk_rows)
    _reap_segments(path, stream.segments)
    _obs.counter("bin_cache_appends_total").inc()
    _obs.counter("bin_cache_appended_rows_total").inc(n_new)
    if upgraded:
        _obs.counter("bin_cache_crc_upgrades_total").inc()
        from ..utils.log import log_warning

        log_warning(
            f"bin cache {path} carried no CRC trailer table (pre-round-13 "
            "format); the append upgraded it — every block of the new "
            "file, old rows included, is now verifiable")
    _obs.event("bin_cache_append", path=os.fspath(path), rows=n_new,
               total_rows=n_old + n_new, upgraded=upgraded)
    return n_old + n_new


def _write_segment(path: str, k: int, bins_new: np.ndarray, dtype,
                   crc_rows: int, label, weight, chunk_rows: int) -> None:
    """One CRC'd sidecar segment, atomically published next to the base
    cache (its own temp + fsync + replace — a crash strands at most a
    temp file the segment scan already skips)."""
    n, f = bins_new.shape
    crc = _CrcTableBuilder(crc_rows, f * np.dtype(dtype).itemsize)

    def _write(fh):
        with zipfile.ZipFile(fh, "w", zipfile.ZIP_STORED) as zf:
            _write_streamed_bins(zf, "bins.npy", n, f, dtype,
                                 array_chunks(bins_new, chunk_rows), crc)
            zf.writestr("bins_crc32.npy", _npy_member_bytes(crc.finish()))
            zf.writestr("bins_crc_rows.npy",
                        _npy_member_bytes(np.asarray(crc_rows, np.int64)))
            zf.writestr("label.npy", _npy_member_bytes(
                label if label is not None else np.zeros(0)))
            zf.writestr("weight.npy", _npy_member_bytes(
                weight if weight is not None else np.zeros(0)))

    _atomic_replace(_seg_path(path, k), _write,
                    os.stat(path).st_mode & 0o7777)


def _reap_segments(path: str, segments) -> None:
    """Best-effort deletion of folded sidecars AFTER the rewrite
    published — a crash in between strands files the watermark already
    excludes from every future read."""
    for _k, sp, _n in segments:
        try:
            os.unlink(sp)
        except OSError:
            pass


def compact_bin_cache(path: str,
                      chunk_rows: int = DEFAULT_CHUNK_ROWS) -> int:
    """Fold every live segment of ``path`` back into its base npz: one
    verified streamed rewrite through the atomic-replace scaffold, then
    the folded sidecars are deleted.  The new base's watermark covers
    every folded index, so the crash window between the replace and the
    deletes is safe — a stranded sidecar is ignored, never
    double-counted.  Returns the total row count (unchanged by
    compaction).  No-op (no rewrite) when no live segments exist."""
    stream = BinCacheStream(path)
    if not stream.segments:
        return stream.shape[0]
    new_label, new_weight, _g, _i, _p = _cache_row_meta(path, stream)
    append_log = (np.asarray(stream.append_log, np.int64)
                  if stream.append_log is not None
                  else np.zeros(0, np.int64))
    watermark = max([s[0] for s in stream.segments]
                    + [stream.seg_watermark])
    _rewrite_cache(path, stream, None, new_label, new_weight,
                   append_log, watermark, chunk_rows)
    _reap_segments(path, stream.segments)
    from ..obs import metrics as _obs

    _obs.counter("bin_cache_compactions_total").inc()
    _obs.event("bin_cache_compact", path=os.fspath(path),
               folded_segments=len(stream.segments),
               total_rows=stream.shape[0], watermark=watermark)
    return stream.shape[0]


def load_segmented_cache(path: str, chunk_rows: int = DEFAULT_CHUNK_ROWS):
    """``(bins, label, weight)`` fully materialized across base + live
    segments — the materialized Dataset loader's segment-aware path —
    or None when the cache has no live segments (the caller's plain
    ``np.load`` view is already complete)."""
    stream = BinCacheStream(path)
    if not stream.segments:
        return None
    out = np.empty((stream.shape[0], stream.shape[1]), stream.dtype)
    for lo, view in stream.chunks(chunk_rows):
        out[lo:lo + view.shape[0]] = view
    label, weight, _g, _i, _p = _cache_row_meta(path, stream)
    return out, label, weight


def array_chunks(arr: np.ndarray,
                 chunk_rows: int) -> Iterator[Tuple[int, np.ndarray]]:
    """The BinCacheStream protocol over an in-memory matrix: row-chunk
    views, zero copies (numpy slices of a C-order array are views)."""
    n = arr.shape[0]
    chunk_rows = max(int(chunk_rows), 1)
    for lo in range(0, n, chunk_rows):
        yield lo, arr[lo:lo + chunk_rows]


def prefetch_device(chunks: Iterator[Tuple[int, np.ndarray]],
                    dtype=None,
                    pad_rows: Optional[int] = None,
                    ) -> Iterator[Tuple[int, int, "object"]]:
    """One-deep prefetch pipeline: upload chunk k+1 to device while the
    consumer computes on chunk k.

    Yields ``(row_lo, valid_rows, device_chunk)``.  With ``pad_rows``
    every device chunk is padded (zero rows) to that fixed row count so
    downstream jitted consumers see ONE shape — one compile for the
    whole sweep; ``valid_rows`` masks the tail.  The upload of the next
    chunk is enqueued BEFORE the current one is yielded: JAX host->device
    transfers are async, so the copy engine overlaps the consumer's
    dispatches instead of serializing after them (the data-feed analogue
    of the windowed driver's one-round-deep pipeline; jaxlint R9: no
    timing is read here, nothing syncs).
    """
    import jax.numpy as jnp

    pad_buf = None

    def _upload(lo: int, view: np.ndarray):
        nonlocal pad_buf
        m = view.shape[0]
        if pad_rows is not None and m < pad_rows:
            if pad_buf is None:
                pad_buf = np.zeros((pad_rows, view.shape[1]), view.dtype)
            pad_buf[:m] = view
            pad_buf[m:] = 0
            host = pad_buf
        else:
            host = view
        # copy=True: the CPU backend can share a numpy buffer zero-copy,
        # and `host` aliases a REUSED staging buffer that the next chunk
        # refills — an aliased upload would corrupt the in-flight chunk
        dev = jnp.array(host, dtype=dtype, copy=True)
        return lo, m, dev

    prev = None
    for lo, view in chunks:
        cur = _upload(lo, view)
        if prev is not None:
            yield prev
        prev = cur
    if prev is not None:
        yield prev
