"""Out-of-core streaming of ``save_binary`` caches (docs round 12).

``Dataset.save_binary`` writes an npz (zip) whose ``bins`` member is the
full (N, F) binned matrix.  ``np.load`` materializes that member whole —
at Higgs-11M x 2000-feature scale the one array is tens of GB, which is
exactly what the out-of-core path must never do.  This module reads the
member the way the reference's two-round loader reads text files:
SEQUENTIALLY, in row chunks, through one reused host buffer.

Key facts the implementation leans on:

* an ``.npy`` payload is a fixed-size header followed by the raw
  C-order element bytes — row ``i`` starts at ``i * F * itemsize``, so
  a sequential read yields whole row chunks with no deserialization;
* a zip member (stored OR deflated) supports streaming reads via
  ``zipfile.ZipFile.open`` — no random access needed, because every
  consumer here sweeps rows front-to-back (ingest fills the device
  matrix once; the spill grower's histogram passes are full sweeps);
* the chunk buffer is allocated ONCE per stream and refilled in place
  (``readinto``) — the "pinned, reused host buffers" contract: steady-
  state streaming does zero per-chunk allocation on the host side.

:class:`BinCacheStream` is the file-backed source; :func:`array_chunks`
is the same protocol over an in-memory matrix (host-RAM datasets whose
DEVICE residency is capped still stream chunk-wise);
:func:`prefetch_device` overlaps the NEXT chunk's host read + device
upload with the consumer's compute on the CURRENT chunk (JAX uploads
are async — enqueueing chunk k+1 before chunk k's consumer dispatches
keeps the copy engine busy without any blocking sync, the round-7
pipelining discipline applied to the data feed).
"""

from __future__ import annotations

import ast
import zipfile
import zlib
from typing import Iterator, Optional, Tuple

import numpy as np

DEFAULT_CHUNK_ROWS = 65536

# fixed CRC32 block size for save_binary caches (rows per CRC entry) —
# independent of the READ chunk size, so any sweep granularity verifies
# against the same trailer table
DEFAULT_CRC_ROWS = 65536


class CorruptBinCacheError(RuntimeError):
    """A ``save_binary`` cache failed integrity verification while
    streaming: a per-chunk CRC32 mismatch, a truncated member, or a
    decompression failure.  Carries the failing CRC chunk and its row
    range, so the error names WHERE the cache is bad instead of letting
    training proceed on garbage bins."""

    def __init__(self, path: str, member: str, chunk_index: int,
                 row_lo: int, row_hi: int, reason: str):
        super().__init__(
            f"{path}:{member} is corrupt at CRC chunk {chunk_index} "
            f"(rows [{row_lo}, {row_hi})): {reason} — the bin cache is "
            "torn or bit-rotted; rebuild it with save_binary "
            "(docs/ROBUSTNESS.md)")
        self.path = path
        self.member = member
        self.chunk_index = chunk_index
        self.row_lo = row_lo
        self.row_hi = row_hi


def bin_crc32s(bins: np.ndarray,
               crc_rows: int = DEFAULT_CRC_ROWS) -> np.ndarray:
    """Per-block CRC32 table over a C-order 2-D binned matrix — the
    values ``save_binary`` stores next to the matrix and
    :class:`BinCacheStream` verifies on read."""
    bins = np.ascontiguousarray(bins)
    crc_rows = max(int(crc_rows), 1)
    out = [zlib.crc32(bins[lo:lo + crc_rows]) & 0xFFFFFFFF
           for lo in range(0, bins.shape[0], crc_rows)]
    return np.asarray(out, np.uint32)


def _read_npy_header(fh) -> Tuple[tuple, np.dtype, bool]:
    """Parse an .npy stream's header: (shape, dtype, fortran_order).
    Reads exactly the header bytes, leaving the stream at element 0."""
    magic = fh.read(6)
    if magic != b"\x93NUMPY":
        raise ValueError("not an .npy stream (bad magic)")
    major, _minor = fh.read(1)[0], fh.read(1)[0]
    if major == 1:
        hlen = int.from_bytes(fh.read(2), "little")
    else:
        hlen = int.from_bytes(fh.read(4), "little")
    header = ast.literal_eval(fh.read(hlen).decode("latin1"))
    return (tuple(header["shape"]), np.dtype(header["descr"]),
            bool(header["fortran_order"]))


class BinCacheStream:
    """Chunked sequential reader of one array member of a save_binary npz.

    ``shape``/``dtype`` come from the member header without reading the
    payload.  :meth:`chunks` yields ``(row_lo, view)`` pairs where
    ``view`` is a window into the SAME reused buffer — consumers must
    copy (device upload copies) before advancing.  Re-iterable: each
    :meth:`chunks` call reopens the member (a fresh sequential
    decompress — the out-of-core price for a full pass).

    ``shard=(row_lo, row_hi)`` restricts the stream to that row range —
    the rank-sharded form for distributed out-of-core training: each
    rank streams ONLY its shard of one shared cache (the fleet manifest
    already fingerprints per-rank shards, docs/ROBUSTNESS.md), paying a
    seek instead of a whole-prefix decompress on the stored (default
    ``save_binary``) members.  ``chunks`` then yields GLOBAL row_lo
    values within [row_lo, row_hi); CRC32 blocks are verified whenever
    the stream covers them from their true start — blocks cut by a shard
    boundary cannot be (their prefix bytes were never read) and are
    skipped, so a whole-cache sweep still verifies everything while a
    shard sweep verifies every fully-covered block."""

    def __init__(self, path: str, member: str = "bins",
                 shard: Optional[Tuple[int, int]] = None) -> None:
        self.path = path
        self.member = member + ".npy"
        with zipfile.ZipFile(path) as zf, zf.open(self.member) as fh:
            shape, dtype, fortran = _read_npy_header(fh)
        if fortran or len(shape) != 2:
            raise ValueError(
                f"{path}:{self.member} must be a C-order 2-D array for row "
                f"streaming (shape={shape}, fortran={fortran})")
        self.shape = shape
        self.dtype = dtype
        if shard is not None:
            lo, hi = int(shard[0]), int(shard[1])
            if not (0 <= lo < hi <= shape[0]):
                raise ValueError(
                    f"shard range [{lo}, {hi}) is outside the cache's "
                    f"{shape[0]} rows")
            self.shard = (lo, hi)
        else:
            self.shard = None
        # per-chunk CRC trailer table (written by save_binary since round
        # 13).  Old trailerless caches still load — with a warning, since
        # nothing can vouch for their bytes.
        self.crc_rows: Optional[int] = None
        self.crcs: Optional[np.ndarray] = None
        try:
            with np.load(path, allow_pickle=False) as z:
                if (f"{member}_crc32" in z.files
                        and f"{member}_crc_rows" in z.files):
                    self.crcs = np.asarray(z[f"{member}_crc32"], np.uint32)
                    self.crc_rows = max(int(z[f"{member}_crc_rows"]), 1)
        except (OSError, ValueError, zipfile.BadZipFile):
            pass  # chunk reads will surface real corruption row-ranged
        if self.crcs is not None:
            expect = -(-self.shape[0] // self.crc_rows) if self.shape[0] else 0
            if len(self.crcs) != expect:
                raise CorruptBinCacheError(
                    path, self.member, 0, 0, min(self.crc_rows,
                                                 self.shape[0]),
                    f"CRC table has {len(self.crcs)} entries, "
                    f"expected {expect}")
        else:
            from ..utils.log import log_warning

            log_warning(
                f"bin cache {path} carries no per-chunk CRC trailers "
                "(pre-round-13 format): reads cannot be verified against "
                "bit-rot — re-run save_binary to upgrade it")

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    @property
    def shard_rows(self) -> int:
        """Rows this stream actually yields (== n_rows without a shard)."""
        if self.shard is None:
            return self.shape[0]
        return self.shard[1] - self.shard[0]

    @property
    def n_cols(self) -> int:
        return self.shape[1]

    def _corrupt(self, row: int, reason: str) -> CorruptBinCacheError:
        crc_rows = self.crc_rows or DEFAULT_CRC_ROWS
        chunk = row // crc_rows
        return CorruptBinCacheError(
            self.path, self.member, chunk, chunk * crc_rows,
            min((chunk + 1) * crc_rows, self.shape[0]), reason)

    def chunks(self, chunk_rows: int) -> Iterator[Tuple[int, np.ndarray]]:
        """Sequential (row_lo, chunk_view) sweep; the view aliases one
        reused buffer of ``chunk_rows`` rows (allocated once here).

        Every sweep re-verifies the per-chunk CRC32 table when the cache
        carries one: the rolling CRC is checked at each CRC-block
        boundary BEFORE the rows completing the block are yielded, so a
        corrupt or truncated cache raises the row-ranged
        :class:`CorruptBinCacheError` at the failing chunk instead of
        feeding garbage bins to training.  (With the default read chunk
        == CRC block size, no unverified row is ever yielded; smaller
        read chunks may see at most one partially-verified trailing
        block's rows before its boundary check runs.)

        With a ``shard`` the sweep covers only [row_lo, row_hi): the
        member is seeked to row_lo (stored members skip the prefix
        without decompressing it) and blocks the shard enters mid-way
        are skipped by verification, never trusted blind — a corrupt
        byte inside any FULLY covered block still raises row-ranged."""
        n, f = self.shape
        lo0, hi0 = self.shard if self.shard is not None else (0, n)
        chunk_rows = max(int(chunk_rows), 1)
        buf = np.empty((chunk_rows, f), self.dtype)  # the reused buffer
        flat = buf.reshape(-1).view(np.uint8)
        row_bytes = f * self.dtype.itemsize
        verify = self.crcs is not None
        crc_cur = 0  # rolling CRC of the current (partial) CRC block
        # a shard entering a CRC block mid-way cannot verify it (the
        # block's leading bytes were never read); arm from the first
        # block the shard covers from its true start
        crc_valid = verify and (not lo0 or lo0 % self.crc_rows == 0)
        with zipfile.ZipFile(self.path) as zf, zf.open(self.member) as fh:
            _read_npy_header(fh)  # skip to element 0
            if lo0:
                try:
                    fh.seek(fh.tell() + lo0 * row_bytes)
                except (OSError, zipfile.BadZipFile, zlib.error) as e:
                    raise self._corrupt(
                        lo0, f"seek to shard start failed: "
                        f"{type(e).__name__}: {e}") from None
            lo = lo0
            while lo < hi0:
                m = min(chunk_rows, hi0 - lo)
                want = m * row_bytes
                got = 0
                mv = memoryview(flat)[:want]
                while got < want:
                    try:
                        k = fh.readinto(mv[got:])
                    except (zipfile.BadZipFile, zlib.error, OSError) as e:
                        raise self._corrupt(
                            lo + got // row_bytes,
                            f"{type(e).__name__}: {e}") from None
                    if not k:
                        raise self._corrupt(lo + got // row_bytes,
                                            "truncated member")
                    got += k
                if verify:
                    # feed the freshly read rows into the rolling CRC,
                    # checking every block boundary they complete
                    pos, row, end_row = 0, lo, lo + m
                    while row < end_row:
                        block = row // self.crc_rows
                        block_end = min((block + 1) * self.crc_rows, n)
                        take = min(block_end, end_row) - row
                        if crc_valid:
                            crc_cur = zlib.crc32(
                                mv[pos:pos + take * row_bytes], crc_cur)
                        pos += take * row_bytes
                        row += take
                        if row == block_end:
                            if crc_valid and (crc_cur & 0xFFFFFFFF) != int(
                                    self.crcs[block]):
                                raise self._corrupt(block_end - 1,
                                                    "CRC32 mismatch")
                            crc_cur = 0
                            crc_valid = verify  # past the shard's cut
                            # block, every block starts from its true head
                yield lo, buf[:m]
                lo += m


def array_chunks(arr: np.ndarray,
                 chunk_rows: int) -> Iterator[Tuple[int, np.ndarray]]:
    """The BinCacheStream protocol over an in-memory matrix: row-chunk
    views, zero copies (numpy slices of a C-order array are views)."""
    n = arr.shape[0]
    chunk_rows = max(int(chunk_rows), 1)
    for lo in range(0, n, chunk_rows):
        yield lo, arr[lo:lo + chunk_rows]


def prefetch_device(chunks: Iterator[Tuple[int, np.ndarray]],
                    dtype=None,
                    pad_rows: Optional[int] = None,
                    ) -> Iterator[Tuple[int, int, "object"]]:
    """One-deep prefetch pipeline: upload chunk k+1 to device while the
    consumer computes on chunk k.

    Yields ``(row_lo, valid_rows, device_chunk)``.  With ``pad_rows``
    every device chunk is padded (zero rows) to that fixed row count so
    downstream jitted consumers see ONE shape — one compile for the
    whole sweep; ``valid_rows`` masks the tail.  The upload of the next
    chunk is enqueued BEFORE the current one is yielded: JAX host->device
    transfers are async, so the copy engine overlaps the consumer's
    dispatches instead of serializing after them (the data-feed analogue
    of the windowed driver's one-round-deep pipeline; jaxlint R9: no
    timing is read here, nothing syncs).
    """
    import jax.numpy as jnp

    pad_buf = None

    def _upload(lo: int, view: np.ndarray):
        nonlocal pad_buf
        m = view.shape[0]
        if pad_rows is not None and m < pad_rows:
            if pad_buf is None:
                pad_buf = np.zeros((pad_rows, view.shape[1]), view.dtype)
            pad_buf[:m] = view
            pad_buf[m:] = 0
            host = pad_buf
        else:
            host = view
        # copy=True: the CPU backend can share a numpy buffer zero-copy,
        # and `host` aliases a REUSED staging buffer that the next chunk
        # refills — an aliased upload would corrupt the in-flight chunk
        dev = jnp.array(host, dtype=dtype, copy=True)
        return lo, m, dev

    prev = None
    for lo, view in chunks:
        cur = _upload(lo, view)
        if prev is not None:
            yield prev
        prev = cur
    if prev is not None:
        yield prev
