"""Text data parsers: CSV / TSV / LibSVM with auto-detection.

Reference: src/io/parser.cpp (Parser::CreateParser auto-detect, CSVParser/
TSVParser/LibSVMParser), src/io/dataset_loader.cpp (label/weight/group column
remap, ignore_column, side files `<data>.weight` / `<data>.query`).

The hot tokenizing loop runs in the native C++ loader (src/native/loader.cpp,
OpenMP) when available; a numpy fallback keeps the package dependency-free.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..native import parse_file_native


def _detect_format(first_line: str) -> str:
    head = first_line.strip()
    toks = head.split()
    if len(toks) >= 2 and ":" in toks[1]:
        return "libsvm"
    if "\t" in head:
        return "tsv"
    return "csv"


def parse_text(text: str, fmt: str = "auto") -> Tuple[np.ndarray, np.ndarray, str]:
    """Parse raw text -> (values (N, C) with NaN for missing, first-col array,
    detected format).  For libsvm returns (label, dense features)."""
    lines = [l for l in text.splitlines() if l.strip() and not l.startswith("#")]
    if not lines:
        return np.zeros((0, 0)), np.zeros(0), "csv"
    if fmt == "auto":
        fmt = _detect_format(lines[0])
    if fmt == "libsvm":
        labels = np.zeros(len(lines))
        rows = []
        maxf = -1
        for i, line in enumerate(lines):
            toks = line.split()
            labels[i] = float(toks[0])
            pairs = []
            for t in toks[1:]:
                if ":" not in t:
                    continue
                k, v = t.split(":", 1)
                k = int(k)
                pairs.append((k, float(v)))
                maxf = max(maxf, k)
            rows.append(pairs)
        data = np.zeros((len(lines), maxf + 1))
        for i, pairs in enumerate(rows):
            for k, v in pairs:
                data[i, k] = v
        return data, labels, fmt
    delim = "\t" if fmt == "tsv" else ","
    ncol = lines[0].count(delim) + 1
    data = np.full((len(lines), ncol), np.nan)
    for i, line in enumerate(lines):
        for j, tok in enumerate(line.rstrip("\r").split(delim)[:ncol]):
            tok = tok.strip()
            if tok and tok.lower() not in ("na", "nan", "null", ""):
                try:
                    data[i, j] = float(tok)
                except ValueError:
                    data[i, j] = np.nan
    return data, data[:, 0].copy(), fmt


def _resolve_column(spec: str, header_names: Optional[List[str]]) -> int:
    """LightGBM column spec: integer index, or `name:<col>` against the
    header (reference: DatasetLoader::SetHeader label_idx resolution)."""
    if spec.startswith("name:"):
        name = spec[5:]
        if header_names and name in header_names:
            return header_names.index(name)
        raise ValueError(f"column name {name!r} not found in header")
    return int(spec)


def load_data_file(
    path: str,
    header: bool = False,
    label_column: str = "",
    weight_column: str = "",
    group_column: str = "",
    ignore_column: str = "",
    fmt: str = "auto",
):
    """Load a training/prediction text file.

    Returns dict(data, label, weight, group, feature_names).
    Side files `<path>.weight` and `<path>.query` are honored like the
    reference (Metadata::LoadWeights/LoadQueryBoundaries).
    """
    with open(path, "r") as fh:
        first = fh.readline()
    fmt_detected = fmt if fmt != "auto" else _detect_format(first)

    header_names: Optional[List[str]] = None
    if header and fmt_detected != "libsvm":
        delim = "\t" if fmt_detected == "tsv" else ","
        header_names = [t.strip() for t in first.rstrip("\n\r").split(delim)]

    label_idx = 0
    if label_column:
        label_idx = _resolve_column(label_column, header_names)
    weight_idx = _resolve_column(weight_column, header_names) if weight_column else -1
    group_idx = _resolve_column(group_column, header_names) if group_column else -1
    ignore_idxs: List[int] = []
    if ignore_column:
        ignore_idxs = [
            _resolve_column(t, header_names) for t in ignore_column.split(",") if t
        ]

    if fmt_detected == "libsvm":
        native = parse_file_native(path, "libsvm", False, 0)
        if native is not None:
            data, label = native
        else:
            with open(path) as fh:
                data, label, _ = parse_text(fh.read(), "libsvm")
        weight = group = None
        names = [f"Column_{i}" for i in range(data.shape[1])]
    else:
        # parse ALL columns (native path keeps the label inline at label_idx=-1
        # so weight/group columns survive), then slice label/weight/group out
        native = parse_file_native(path, fmt_detected, header, -1)
        if native is not None:
            cols, _ = native
        else:
            with open(path) as fh:
                text = fh.read()
            if header:
                text = text.split("\n", 1)[1] if "\n" in text else ""
            cols, _, _ = parse_text(text, fmt_detected)
        ncol = cols.shape[1]
        label = cols[:, label_idx].copy() if 0 <= label_idx < ncol else np.zeros(len(cols))
        weight = cols[:, weight_idx].copy() if 0 <= weight_idx < ncol else None
        group = cols[:, group_idx].copy() if 0 <= group_idx < ncol else None
        drop = {label_idx, *ignore_idxs}
        if weight_idx >= 0:
            drop.add(weight_idx)
        if group_idx >= 0:
            drop.add(group_idx)
        keep = [j for j in range(ncol) if j not in drop]
        data = cols[:, keep]
        if header_names:
            names = [header_names[j] for j in keep]
        else:
            names = [f"Column_{j}" for j in keep]

    # side files (reference: Metadata::LoadWeights / LoadQueryBoundaries)
    if weight is None and os.path.exists(path + ".weight"):
        weight = np.loadtxt(path + ".weight", dtype=np.float64).reshape(-1)
    query = None
    if os.path.exists(path + ".query"):
        query = np.loadtxt(path + ".query", dtype=np.int64).reshape(-1)
    elif group is not None:
        # group column holds a query id per row -> convert to group sizes
        _, counts = np.unique(group, return_counts=True)
        # preserve file order of query ids
        ids, idx = np.unique(group, return_index=True)
        order = np.argsort(idx)
        sizes = np.zeros(len(ids), np.int64)
        for rank, o in enumerate(order):
            sizes[rank] = counts[o]
        query = sizes

    return dict(data=data, label=label, weight=weight, group=query,
                feature_names=names)
