"""Text data parsers: CSV / TSV / LibSVM with auto-detection.

Reference: src/io/parser.cpp (Parser::CreateParser auto-detect, CSVParser/
TSVParser/LibSVMParser), src/io/dataset_loader.cpp (label/weight/group column
remap, ignore_column, side files `<data>.weight` / `<data>.query`).

The hot tokenizing loop runs in the native C++ loader (src/native/loader.cpp,
OpenMP) when available; a numpy fallback keeps the package dependency-free.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..native import parse_file_native


def _detect_format(first_line: str) -> str:
    head = first_line.strip()
    toks = head.split()
    if len(toks) >= 2 and ":" in toks[1]:
        return "libsvm"
    if "\t" in head:
        return "tsv"
    return "csv"


def parse_text(text: str, fmt: str = "auto") -> Tuple[np.ndarray, np.ndarray, str]:
    """Parse raw text -> (values (N, C) with NaN for missing, first-col array,
    detected format).  For libsvm returns (label, dense features)."""
    lines = [l for l in text.splitlines() if l.strip() and not l.startswith("#")]
    if not lines:
        return np.zeros((0, 0)), np.zeros(0), "csv"
    if fmt == "auto":
        fmt = _detect_format(lines[0])
    if fmt == "libsvm":
        labels = np.zeros(len(lines))
        rows = []
        maxf = -1
        for i, line in enumerate(lines):
            toks = line.split()
            labels[i] = float(toks[0])
            pairs = []
            for t in toks[1:]:
                if ":" not in t:
                    continue
                k, v = t.split(":", 1)
                k = int(k)
                pairs.append((k, float(v)))
                maxf = max(maxf, k)
            rows.append(pairs)
        data = np.zeros((len(lines), maxf + 1))
        for i, pairs in enumerate(rows):
            for k, v in pairs:
                data[i, k] = v
        return data, labels, fmt
    delim = "\t" if fmt == "tsv" else ","
    ncol = lines[0].count(delim) + 1
    data = np.full((len(lines), ncol), np.nan)
    for i, line in enumerate(lines):
        for j, tok in enumerate(line.rstrip("\r").split(delim)[:ncol]):
            tok = tok.strip()
            if tok and tok.lower() not in ("na", "nan", "null", ""):
                try:
                    data[i, j] = float(tok)
                except ValueError:
                    data[i, j] = np.nan
    return data, data[:, 0].copy(), fmt


def _resolve_column(spec: str, header_names: Optional[List[str]]) -> int:
    """LightGBM column spec: integer index, or `name:<col>` against the
    header (reference: DatasetLoader::SetHeader label_idx resolution)."""
    if spec.startswith("name:"):
        name = spec[5:]
        if header_names and name in header_names:
            return header_names.index(name)
        raise ValueError(f"column name {name!r} not found in header")
    return int(spec)



def _file_column_spec(path: str, fmt: str, header: bool, label_column: str,
                      weight_column: str, group_column: str,
                      ignore_column: str):
    """Shared header/format sniffing + column-index resolution for BOTH the
    eager and the two-round loaders (one implementation so the two modes
    cannot drift)."""
    with open(path, "r") as fh:
        first = fh.readline()
    fmt_detected = fmt if fmt != "auto" else _detect_format(first)
    header_names: Optional[List[str]] = None
    if header and fmt_detected != "libsvm":
        delim = "\t" if fmt_detected == "tsv" else ","
        header_names = [t.strip() for t in first.rstrip("\n\r").split(delim)]
    if fmt_detected == "libsvm":
        return fmt_detected, None, -1, -1, -1, []
    label_idx = _resolve_column(label_column, header_names) if label_column else 0
    weight_idx = _resolve_column(weight_column, header_names) if weight_column else -1
    group_idx = _resolve_column(group_column, header_names) if group_column else -1
    ignore_idxs = [
        _resolve_column(t, header_names) for t in (ignore_column or "").split(",") if t
    ]
    return fmt_detected, header_names, label_idx, weight_idx, group_idx, ignore_idxs


def _split_columns(cols: np.ndarray, label_idx: int, weight_idx: int,
                   group_idx: int, ignore_idxs: List[int]):
    """Split a parsed all-columns chunk into (features, label, weight, group)
    with the same out-of-range tolerance in both loaders."""
    ncol = cols.shape[1]
    label = (cols[:, label_idx].copy() if 0 <= label_idx < ncol
             else np.zeros(len(cols)))
    weight = cols[:, weight_idx].copy() if 0 <= weight_idx < ncol else None
    group = cols[:, group_idx].copy() if 0 <= group_idx < ncol else None
    drop = {label_idx, *ignore_idxs}
    if 0 <= weight_idx < ncol:
        drop.add(weight_idx)
    if 0 <= group_idx < ncol:
        drop.add(group_idx)
    keep = [j for j in range(ncol) if j not in drop]
    return cols[:, keep], label, weight, group, keep


def _group_ids_to_sizes(gcol: np.ndarray) -> np.ndarray:
    """Query-id column -> group sizes, preserving file order of query ids
    (reference: Metadata group column semantics)."""
    ids, idx = np.unique(gcol, return_index=True)
    _, counts = np.unique(gcol, return_counts=True)
    order = np.argsort(idx)
    sizes = np.zeros(len(ids), np.int64)
    for rank, o in enumerate(order):
        sizes[rank] = counts[o]
    return sizes


def load_data_file(
    path: str,
    header: bool = False,
    label_column: str = "",
    weight_column: str = "",
    group_column: str = "",
    ignore_column: str = "",
    fmt: str = "auto",
):
    """Load a training/prediction text file.

    Returns dict(data, label, weight, group, feature_names).
    Side files `<path>.weight` and `<path>.query` are honored like the
    reference (Metadata::LoadWeights/LoadQueryBoundaries).
    """
    fmt_detected, header_names, label_idx, weight_idx, group_idx, ignore_idxs = (
        _file_column_spec(path, fmt, header, label_column, weight_column,
                          group_column, ignore_column)
    )

    if fmt_detected == "libsvm":
        native = parse_file_native(path, "libsvm", False, 0)
        if native is not None:
            data, label = native
        else:
            with open(path) as fh:
                data, label, _ = parse_text(fh.read(), "libsvm")
        weight = group = None
        names = [f"Column_{i}" for i in range(data.shape[1])]
    else:
        # parse ALL columns (native path keeps the label inline at label_idx=-1
        # so weight/group columns survive), then slice label/weight/group out
        native = parse_file_native(path, fmt_detected, header, -1)
        if native is not None:
            cols, _ = native
        else:
            with open(path) as fh:
                text = fh.read()
            if header:
                text = text.split("\n", 1)[1] if "\n" in text else ""
            cols, _, _ = parse_text(text, fmt_detected)
        data, label, weight, group, keep = _split_columns(
            cols, label_idx, weight_idx, group_idx, ignore_idxs
        )
        if header_names:
            names = [header_names[j] for j in keep]
        else:
            names = [f"Column_{j}" for j in keep]

    # side files (reference: Metadata::LoadWeights / LoadQueryBoundaries)
    if weight is None and os.path.exists(path + ".weight"):
        weight = np.loadtxt(path + ".weight", dtype=np.float64).reshape(-1)
    query = None
    if os.path.exists(path + ".query"):
        query = np.loadtxt(path + ".query", dtype=np.int64).reshape(-1)
    elif group is not None:
        query = _group_ids_to_sizes(group)

    return dict(data=data, label=label, weight=weight, group=query,
                feature_names=names)


def _prefetch(it, depth: int = 1):
    """Async double-buffered iteration (reference:
    include/LightGBM/utils/pipeline_reader.h — PipelineReader overlaps the
    next block's read+parse with the consumer's work).  depth=1 is true
    double buffering: one chunk parsing ahead while one is consumed.
    Worker exceptions re-raise at the consuming site; if the consumer exits
    early, the worker is unblocked and the source iterator closed so no
    thread or file handle leaks."""
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    _END, _ERR = object(), object()
    stop = threading.Event()

    def worker():
        try:
            for item in it:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 - re-raised at consumer
            q.put((_ERR, e))
            return
        finally:
            if stop.is_set():
                it.close()  # unwind the source's `with open(...)`
        q.put(_END)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                break
            if isinstance(item, tuple) and len(item) == 2 and item[0] is _ERR:
                raise item[1]
            yield item
    finally:
        stop.set()
        while not q.empty():  # unblock a worker waiting in q.put
            try:
                q.get_nowait()
            except queue.Empty:
                break


def _iter_chunks(path: str, fmt: str, header: bool, chunk_rows: int):
    """Yield parsed (columns, first_col) chunks of a CSV/TSV/LibSVM file
    without ever holding the whole file (reference: TextReader's chunked
    reads + PipelineReader).  LibSVM chunks are as wide as their own widest
    feature index; the caller reconciles widths."""
    buf: List[str] = []
    with open(path, "r") as fh:
        if header and fmt != "libsvm":
            fh.readline()
        for line in fh:
            if not line.strip() or line.startswith("#"):
                continue
            buf.append(line)
            if len(buf) >= chunk_rows:
                yield parse_text("".join(buf), fmt)[0:2]
                buf = []
    if buf:
        yield parse_text("".join(buf), fmt)[0:2]


def load_data_file_two_round(
    path: str,
    binner_factory,
    header: bool = False,
    label_column: str = "",
    weight_column: str = "",
    group_column: str = "",
    ignore_column: str = "",
    fmt: str = "auto",
    sample_cnt: int = 200000,
    chunk_rows: int = 200000,
    seed: int = 1,
    sample_needed: bool = True,
):
    """Two-pass streaming load (reference: DatasetLoader::LoadFromFile with
    two_round=true — the file is read twice and the raw float matrix is
    NEVER materialized): pass 1 reservoir-samples rows and counts them;
    `binner_factory(sample, feature_names)` fits (or supplies) bin mappers;
    pass 2 streams chunks through the binner into a preallocated compact bin
    matrix.  Column semantics are shared with load_data_file via
    _file_column_spec/_split_columns.

    Returns dict(binner, bins, label, weight, group, feature_names).
    """
    fmt_detected, header_names, label_idx, weight_idx, group_idx, ignore_idxs = (
        _file_column_spec(path, fmt, header, label_column, weight_column,
                          group_column, ignore_column)
    )
    rng = np.random.RandomState(seed)

    def split_chunk(cols, lab):
        if fmt_detected == "libsvm":
            return cols, lab, None, None
        return _split_columns(cols, label_idx, weight_idx, group_idx,
                              ignore_idxs)[:4]

    # ---- pass 1: row count + reservoir sample (Vitter's algorithm R) ----
    # (sample_needed=False — a pre-supplied reference binner — only counts
    # rows and reconciles the width; no sample is built)
    sample = None
    n_seen = 0
    n_feat = 0
    for cols, lab in _prefetch(_iter_chunks(path, fmt_detected, header, chunk_rows)):
        feats = split_chunk(cols, lab)[0]
        n_feat = max(n_feat, feats.shape[1])
        n_seen += feats.shape[0]
        if not sample_needed:
            continue
        if feats.shape[1] < n_feat:  # libsvm ragged width
            feats = np.pad(feats, ((0, 0), (0, n_feat - feats.shape[1])))
        if sample is None:
            sample = np.empty((0, n_feat), np.float64)
        elif sample.shape[1] < n_feat:
            sample = np.pad(sample, ((0, 0), (0, n_feat - sample.shape[1])))
        seen_before = n_seen - feats.shape[0]
        need = sample_cnt - len(sample)
        if need > 0:
            sample = np.concatenate([sample, feats[:need].copy()], axis=0)
            rest = feats[need:]
            base = seen_before + min(need, feats.shape[0])
        else:
            rest = feats
            base = seen_before
        if len(rest):
            # vectorized reservoir step: row i replaces slot js[i] when
            # js[i] < sample_cnt, with js[i] uniform on [0, base + i]
            js = (rng.random(len(rest))
                  * (base + np.arange(len(rest)) + 1)).astype(np.int64)
            hit = js < sample_cnt
            sample[js[hit]] = rest[hit]

    if n_seen == 0:
        raise ValueError(f"empty data file: {path}")

    if header_names:
        drop = {label_idx, weight_idx, group_idx, *ignore_idxs}
        names = [header_names[j] for j in range(len(header_names)) if j not in drop]
    else:
        names = [f"Column_{i}" for i in range(n_feat)]

    binner = binner_factory(sample, names)
    del sample
    if binner.num_features > n_feat:
        # a reference binner may be wider than this file (e.g. a LibSVM
        # valid set missing the rarest feature indices): pad to its width
        n_feat = binner.num_features

    # ---- pass 2: stream chunks through the binner into the bin matrix ----
    dtype = np.uint8 if binner.max_num_bins <= 256 else np.int32
    bins = np.empty((n_seen, n_feat), dtype=dtype)
    labels = np.empty(n_seen, np.float64)
    weights = [] if (fmt_detected != "libsvm" and weight_idx >= 0) else None
    groups = [] if (fmt_detected != "libsvm" and group_idx >= 0) else None
    lo = 0
    for cols, lab in _prefetch(_iter_chunks(path, fmt_detected, header, chunk_rows)):
        feats, label, weight, group = split_chunk(cols, lab)
        if fmt_detected == "libsvm":
            label = lab
        if feats.shape[1] < n_feat:
            feats = np.pad(feats, ((0, 0), (0, n_feat - feats.shape[1])))
        hi = lo + feats.shape[0]
        bins[lo:hi] = binner.transform(feats).astype(dtype)
        labels[lo:hi] = label
        if weights is not None:
            # _split_columns already copies, so no chunk view is retained
            weights.append(weight if weight is not None
                           else np.ones(feats.shape[0]))
        if groups is not None:
            groups.append(group if group is not None
                          else np.zeros(feats.shape[0]))
        lo = hi

    weight_arr = np.concatenate(weights) if weights else None
    if weight_arr is None and os.path.exists(path + ".weight"):
        weight_arr = np.loadtxt(path + ".weight", dtype=np.float64).reshape(-1)
    # side-file precedence matches load_data_file: .query wins over a column
    group_arr = None
    if os.path.exists(path + ".query"):
        group_arr = np.loadtxt(path + ".query", dtype=np.int64).reshape(-1)
    elif groups:
        group_arr = _group_ids_to_sizes(np.concatenate(groups))

    return dict(binner=binner, bins=bins, label=labels, weight=weight_arr,
                group=group_arr, feature_names=names)
