"""Exclusive Feature Bundling (EFB).

Reference: src/io/dataset_loader.cpp -> DatasetLoader::FindGroups /
FastFeatureBundling and the NeurIPS'17 LightGBM paper §4.  Sparse,
mutually-exclusive features (e.g. one-hot blocks) are merged into single
"bundle" columns so the histogram pass scans F_b << F columns.

TPU-first redesign: the reference interleaves bundling with its FeatureGroup
bin storage; here bundling is a pure host-side preprocessing that emits
  * a bundled bin matrix (N, F_b) in the SAME bin-width budget B as the
    original features (bundle capacity is capped at B so the Pallas
    histogram kernel shape is unchanged — fewer columns, same lanes), and
  * gather/default tables that UNBUNDLE a bundle histogram back into
    per-original-feature histograms on device (ops/treegrow_fast.py), so
    split search, tree structure, partitioning and prediction all stay in
    original-feature space (mirroring the reference, whose trees never
    reference bundles).

Bundle bin layout (zero-conflict, like the reference's exclusive bundles):
bin 0 = every member at its default (most frequent) bin; member j with nb_j
bins contributes nb_j - 1 slots at offset off_j, one per non-default bin in
ascending order.  A feature's default-bin histogram row is recovered as
leaf_total - sum(its non-default slots) — the reference's most-freq-bin
subtraction trick.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np


class FeatureBundles(NamedTuple):
    bundles: List[List[int]]  # member original-feature ids per bundle
    bundled_bins: Optional[np.ndarray]  # (N, F_b) int32
    bundled_num_bins: np.ndarray  # (F_b,) int32
    gather_idx: np.ndarray  # (F, B) int32 into flat (F_b*B,) (+1 zero pad at F_b*B)
    default_mask: np.ndarray  # (F, B) bool — the default slot per feature
    num_bundled: int  # F_b
    default_bin: np.ndarray  # (F,) int32 — most frequent bin per feature

    @property
    def is_useful(self) -> bool:
        return self.num_bundled < len(self.gather_idx)


def apply_bundles(efb: "FeatureBundles", bins: np.ndarray,
                  num_bins_pf: np.ndarray) -> np.ndarray:
    """Re-bundle a (same-binner) bin matrix with an existing bundle plan —
    used when a dataset is constructed with reference= another dataset."""
    n = bins.shape[0]
    out = np.zeros((n, efb.num_bundled), np.int32)
    for g, members in enumerate(efb.bundles):
        if len(members) == 1:
            out[:, g] = bins[:, members[0]]
            continue
        off = 1
        col = np.zeros(n, np.int32)
        for j in members:
            nb = int(num_bins_pf[j])
            d = int(efb.default_bin[j])
            v = bins[:, j]
            nd = v != d
            col = np.where(nd, off + (v - (v > d)), col)
            off += nb - 1
        out[:, g] = col
    return out


def find_bundles(
    bins: np.ndarray,  # (N, F) int
    num_bins_pf: np.ndarray,  # (F,)
    max_total_bins: int,  # B — bundle capacity (kernel lane budget)
    categorical_mask: Optional[np.ndarray] = None,
    sample_cnt: int = 200_000,
    max_conflict_rate: float = 0.0,
    min_sparse_rate: float = 0.8,
    seed: int = 0,
) -> Optional[FeatureBundles]:
    """Greedy conflict-free bundling (reference: FindGroups' greedy graph
    coloring over the feature-conflict graph, conflict counts estimated on a
    row sample).  Returns None when bundling would not reduce the column
    count (dense data)."""
    n, f = bins.shape
    if f < 3:
        return None
    rng = np.random.RandomState(seed)
    if n > sample_cnt:
        rows = rng.choice(n, size=sample_cnt, replace=False)
        sample = bins[rows]
    else:
        sample = bins
    ns = sample.shape[0]

    # default (most frequent) bin per feature, estimated on the sample
    default_bin = np.zeros(f, np.int32)
    nondefault_cnt = np.zeros(f, np.int64)
    for j in range(f):
        bc = np.bincount(sample[:, j], minlength=int(num_bins_pf[j]))
        default_bin[j] = int(bc.argmax())
        nondefault_cnt[j] = ns - bc.max()

    sparse = nondefault_cnt <= ns * (1.0 - min_sparse_rate)
    if categorical_mask is not None:
        sparse &= ~np.asarray(categorical_mask, bool)
    if sparse.sum() < 2:
        return None

    # packed non-default masks for fast conflict counting
    nd_bits = {}
    for j in np.flatnonzero(sparse):
        nd_bits[j] = np.packbits(sample[:, j] != default_bin[j])

    max_conflicts = int(max_conflict_rate * ns)
    order = sorted(nd_bits, key=lambda j: -nondefault_cnt[j])
    bundle_members: List[List[int]] = []
    bundle_bits: List[np.ndarray] = []
    bundle_width: List[int] = []  # used slots incl. slot 0
    for j in order:
        w = int(num_bins_pf[j]) - 1  # non-default slots
        placed = False
        for g in range(len(bundle_members)):
            if bundle_width[g] + w > max_total_bins:
                continue
            conflicts = int(
                np.unpackbits(bundle_bits[g] & nd_bits[j])[:ns].sum()
            )
            if conflicts <= max_conflicts:
                bundle_members[g].append(j)
                bundle_bits[g] = bundle_bits[g] | nd_bits[j]
                bundle_width[g] += w
                placed = True
                break
        if not placed:
            bundle_members.append([j])
            bundle_bits.append(nd_bits[j].copy())
            bundle_width.append(1 + w)

    multi = [m for m in bundle_members if len(m) > 1]
    if not multi:
        return None

    # final bundle list: multi-member bundles first, then singletons for every
    # remaining feature (dense, categorical, or unplaced)
    in_multi = {j for m in multi for j in m}
    singles = [[j] for j in range(f) if j not in in_multi]
    bundles = multi + singles
    fb = len(bundles)
    # gather/table stride = the widest ACTUAL column (bundle or single
    # feature), not the packing capacity — capacity may be the full max_bin
    # budget while e.g. one-hot bundles pack far narrower, and this stride
    # becomes the dataset's histogram width
    B = max(
        max(
            (1 + sum(int(num_bins_pf[j]) - 1 for j in m)) if len(m) > 1
            else int(num_bins_pf[m[0]])
            for m in bundles
        ),
        1,
    )

    bundled_num_bins = np.zeros(fb, np.int32)
    gather_idx = np.full((f, B), fb * B, np.int64)  # default -> zero pad slot
    default_mask = np.zeros((f, B), bool)
    for g, members in enumerate(bundles):
        if len(members) == 1:
            j = members[0]
            nb = int(num_bins_pf[j])
            bundled_num_bins[g] = nb
            gather_idx[j, :nb] = g * B + np.arange(nb)
            continue
        off = 1
        for j in members:
            nb = int(num_bins_pf[j])
            d = int(default_bin[j])
            nd_bins = np.setdiff1d(np.arange(nb), [d])
            gather_idx[j, nd_bins] = g * B + off + np.arange(nb - 1)
            default_mask[j, d] = True
            off += nb - 1
        bundled_num_bins[g] = off

    plan = FeatureBundles(
        bundles=bundles,
        bundled_bins=None,
        bundled_num_bins=bundled_num_bins,
        gather_idx=gather_idx.astype(np.int32),
        default_mask=default_mask,
        num_bundled=fb,
        default_bin=default_bin,
    )
    # the bundled matrix is produced by the ONE shared encoder so plan
    # construction and reference-dataset re-bundling cannot drift
    return plan._replace(bundled_bins=apply_bundles(plan, bins, num_bins_pf))
