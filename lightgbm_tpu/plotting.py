"""Plotting utilities (reference: python-package/lightgbm/plotting.py).

Same public surface as the reference: plot_importance, plot_split_value_
histogram, plot_metric, plot_tree, create_tree_digraph.  matplotlib and
graphviz are optional — each entry point raises ImportError with the same
kind of message the reference uses when the backend is missing.
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster

__all__ = [
    "plot_importance",
    "plot_split_value_histogram",
    "plot_metric",
    "plot_tree",
    "create_tree_digraph",
]


def _check_not_tuple_of_2_elements(obj, obj_name):
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a tuple of 2 elements.")


def _float2str(value: float, precision: Optional[int]) -> str:
    return (
        f"{value:.{precision}f}"
        if precision is not None and not isinstance(value, str)
        else str(value)
    )


def _get_booster(booster) -> Booster:
    from .sklearn import LGBMModel

    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be Booster or LGBMModel.")


def _import_matplotlib():
    try:
        import matplotlib.pyplot as plt  # noqa: F401

        return plt
    except ImportError as e:
        raise ImportError("You must install matplotlib and restart your session to plot.") from e


def plot_importance(
    booster,
    ax=None,
    height: float = 0.2,
    xlim: Optional[Tuple[float, float]] = None,
    ylim: Optional[Tuple[float, float]] = None,
    title: Optional[str] = "Feature importance",
    xlabel: Optional[str] = "Feature importance",
    ylabel: Optional[str] = "Features",
    importance_type: str = "auto",
    max_num_features: Optional[int] = None,
    ignore_zero: bool = True,
    figsize: Optional[Tuple[float, float]] = None,
    dpi: Optional[int] = None,
    grid: bool = True,
    precision: Optional[int] = 3,
    **kwargs,
):
    """Horizontal bar chart of feature importance (reference:
    plotting.py plot_importance)."""
    plt = _import_matplotlib()
    bst = _get_booster(booster)
    if importance_type == "auto":
        importance_type = (
            getattr(booster, "importance_type", "split")
            if not isinstance(booster, Booster)
            else "split"
        )
    importance = bst.feature_importance(importance_type=importance_type)
    feature_name = bst.feature_name()
    if not len(importance):
        raise ValueError("Booster's feature_importance is empty.")

    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples)

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(
            x + 1,
            y,
            _float2str(x, precision) if importance_type == "gain" else str(int(x)),
            va="center",
        )
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, max(values) * 1.1)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (-1, len(values))
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        xlabel = xlabel.replace("@importance_type@", importance_type)
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(
    booster,
    feature: Union[int, str],
    bins=None,
    ax=None,
    width_coef: float = 0.8,
    xlim=None,
    ylim=None,
    title: Optional[str] = "Split value histogram for feature with @index/name@ @feature@",
    xlabel: Optional[str] = "Feature split value",
    ylabel: Optional[str] = "Count",
    figsize=None,
    dpi=None,
    grid: bool = True,
    **kwargs,
):
    """Histogram of a feature's split thresholds across the model
    (reference: plotting.py plot_split_value_histogram)."""
    plt = _import_matplotlib()
    bst = _get_booster(booster)

    hist, split_bins = bst.get_split_value_histogram(feature=feature, bins=bins, xgboost_style=False)
    if np.count_nonzero(hist) == 0:
        raise ValueError(f"Cannot plot split value histogram, because feature {feature} was not used in splitting")
    width = width_coef * (split_bins[1] - split_bins[0])
    centred = (split_bins[:-1] + split_bins[1:]) / 2

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    ax.bar(centred, hist, align="center", width=width, **kwargs)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        range_result = split_bins[-1] - split_bins[0]
        xlim = (split_bins[0] - range_result * 0.2, split_bins[-1] + range_result * 0.2)
    from matplotlib.ticker import MaxNLocator

    ax.set_xlim(xlim)
    ax.yaxis.set_major_locator(MaxNLocator(integer=True))
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (0, max(hist) * 1.1)
    ax.set_ylim(ylim)
    if title is not None:
        title = title.replace("@feature@", str(feature))
        title = title.replace("@index/name@", "name" if isinstance(feature, str) else "index")
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(
    booster,
    metric: Optional[str] = None,
    dataset_names: Optional[List[str]] = None,
    ax=None,
    xlim=None,
    ylim=None,
    title: Optional[str] = "Metric during training",
    xlabel: Optional[str] = "Iterations",
    ylabel: Optional[str] = "@metric@",
    figsize=None,
    dpi=None,
    grid: bool = True,
):
    """Plot metric curves recorded by record_evaluation (reference:
    plotting.py plot_metric; accepts the eval-result dict or a fitted
    sklearn estimator, NOT a raw Booster — same contract)."""
    plt = _import_matplotlib()
    from .sklearn import LGBMModel

    if isinstance(booster, LGBMModel):
        eval_results = deepcopy(booster.evals_result_)
    elif isinstance(booster, dict):
        eval_results = deepcopy(booster)
    elif isinstance(booster, Booster):
        raise TypeError(
            "booster must be dict or LGBMModel. To use plot_metric with Booster type, "
            "first record eval results using record_evaluation callback."
        )
    else:
        raise TypeError("booster must be dict or LGBMModel.")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    if dataset_names is None:
        dataset_names_iter = iter(eval_results.keys())
    elif not dataset_names:
        raise ValueError("dataset_names cannot be empty.")
    else:
        dataset_names_iter = iter(dataset_names)

    name = next(dataset_names_iter)
    metrics_for_one = eval_results[name]
    num_metric = len(metrics_for_one)
    if metric is None:
        if num_metric > 1:
            raise ValueError("more than one metric available, pass metric parameter to plot specific one.")
        metric, results = metrics_for_one.popitem()
    else:
        if metric not in metrics_for_one:
            raise KeyError("No given metric in eval results.")
        results = metrics_for_one[metric]
    num_iteration = len(results)
    max_result = max(results)
    min_result = min(results)
    x_ = range(num_iteration)
    ax.plot(x_, results, label=name)

    for name in dataset_names_iter:
        metrics_for_one = eval_results[name]
        results = metrics_for_one[metric]
        max_result = max(*results, max_result)
        min_result = min(*results, min_result)
        ax.plot(x_, results, label=name)

    ax.legend(loc="best")
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, num_iteration)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        range_result = max_result - min_result
        ylim = (min_result - range_result * 0.2, max_result + range_result * 0.2)
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ylabel = ylabel.replace("@metric@", metric)
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _to_graphviz(
    tree_info: Dict[str, Any],
    show_info: List[str],
    feature_names: List[str],
    precision: Optional[int],
    orientation: str,
    **kwargs,
):
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError("You must install graphviz and restart your session to plot tree.") from e

    def add(root, total_count, parent=None, decision=None):
        """Recursively add node or edge (reference: plotting.py _to_graphviz.add)."""
        if "split_index" in root:
            name = f"split{root['split_index']}"
            if feature_names is not None:
                label = f"<B>{feature_names[root['split_feature']]}</B>"
            else:
                label = f"feature <B>{root['split_feature']}</B>"
            direction = "&#8804;" if root["decision_type"] == "<=" else "="
            label += f" {direction} <B>{_float2str(root['threshold'], precision)}</B>"
            for info in ["split_gain", "internal_value", "internal_weight", "internal_count", "data_percentage"]:
                if info in show_info:
                    output = info.split("_")[-1]
                    if info in {"split_gain", "internal_value", "internal_weight"}:
                        label += f"<br/>{_float2str(root[info], precision)} {output}"
                    elif info == "internal_count":
                        label += f"<br/>{output}: {root[info]}"
                    else:
                        label += f"<br/>{_float2str(root['internal_count'] / total_count * 100, 2)}% of data"
            fillcolor = "white"
            style = ""
            graph.node(name, label=f"<{label}>", shape="rectangle", style=style, fillcolor=fillcolor)
            add(root["left_child"], total_count, name, "yes")
            add(root["right_child"], total_count, name, "no")
        else:  # leaf
            name = f"leaf{root['leaf_index']}"
            label = f"leaf {root['leaf_index']}: "
            label += f"<B>{_float2str(root['leaf_value'], precision)}</B>"
            if "leaf_weight" in show_info:
                label += f"<br/>{_float2str(root['leaf_weight'], precision)} weight"
            if "leaf_count" in show_info:
                label += f"<br/>count: {root['leaf_count']}"
            if "data_percentage" in show_info:
                label += f"<br/>{_float2str(root['leaf_count'] / total_count * 100, 2)}% of data"
            graph.node(name, label=f"<{label}>")
        if parent is not None:
            graph.edge(parent, name, decision)

    graph = Digraph(**kwargs)
    rankdir = "LR" if orientation == "horizontal" else "TB"
    graph.attr("graph", nodesep="0.05", ranksep="0.3", rankdir=rankdir)
    if "internal_count" in tree_info["tree_structure"]:
        add(tree_info["tree_structure"], tree_info["tree_structure"]["internal_count"])
    else:
        raise Exception("Cannot plot trees with no split")
    return graph


def create_tree_digraph(
    booster,
    tree_index: int = 0,
    show_info: Optional[List[str]] = None,
    precision: Optional[int] = 3,
    orientation: str = "horizontal",
    **kwargs,
):
    """Create a graphviz Digraph of a single tree (reference: plotting.py
    create_tree_digraph)."""
    bst = _get_booster(booster)
    model = bst.dump_model()
    tree_infos = model["tree_info"]
    feature_names = model.get("feature_names", None)
    if tree_index < len(tree_infos):
        tree_info = tree_infos[tree_index]
    else:
        raise IndexError("tree_index is out of range.")
    if show_info is None:
        show_info = []
    return _to_graphviz(tree_info, show_info, feature_names, precision, orientation, **kwargs)


def plot_tree(
    booster,
    ax=None,
    tree_index: int = 0,
    figsize=None,
    dpi=None,
    show_info: Optional[List[str]] = None,
    precision: Optional[int] = 3,
    orientation: str = "horizontal",
    **kwargs,
):
    """Render one tree with matplotlib via graphviz (reference: plotting.py
    plot_tree)."""
    plt = _import_matplotlib()
    from matplotlib.image import imread

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    graph = create_tree_digraph(
        booster=booster, tree_index=tree_index, show_info=show_info,
        precision=precision, orientation=orientation, **kwargs,
    )
    from io import BytesIO

    s = BytesIO()
    s.write(graph.pipe(format="png"))
    s.seek(0)
    img = imread(s)
    ax.imshow(img)
    ax.axis("off")
    return ax
